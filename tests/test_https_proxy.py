"""HTTPS interception: CA forging, CONNECT hijack, SNI proxy.

Reference: client/daemon/proxy/proxy.go:471 handleHTTPS (TLS hijack with
forged leaf certs so HTTPS registry pulls ride P2P) and proxy_sni.go (SNI
routing for direct-TLS clients). The round-1 CONNECT handler was a blind
byte relay, which meant every real containerd pull (BASELINE config #3)
bypassed the fabric entirely.
"""

from __future__ import annotations

import asyncio
import hashlib
import os
import random
import ssl

import aiohttp
import pytest
from aiohttp import web

pytest.importorskip(
    "cryptography",
    reason="MITM CA needs the cryptography package (absent in slim images)")

from dragonfly2_tpu.daemon.proxy import Proxy, parse_sni
from dragonfly2_tpu.daemon.transport import P2PTransport, ProxyRule
from dragonfly2_tpu.pkg.certify import CertAuthority
from dragonfly2_tpu.pkg.piece import Range

from tests.test_stream_proxy import make_task_manager

BLOB = bytes(random.Random(13).randbytes(4 * 1024 * 1024))
BLOB_SHA = hashlib.sha256(BLOB).hexdigest()

_CA = None


def shared_ca() -> CertAuthority:
    """One CA per test session — RSA keygen is the slow part."""
    global _CA
    if _CA is None:
        _CA = CertAuthority.generate()
    return _CA


async def start_tls_registry(ca: CertAuthority):
    """Fake HTTPS OCI registry with Range support and hit counting."""
    stats = {"blob_gets": 0}

    async def blob(request: web.Request) -> web.Response:
        stats["blob_gets"] += 1
        rng = request.headers.get("Range")
        if rng:
            r = Range.parse_http(rng, len(BLOB))
            return web.Response(
                status=206, body=BLOB[r.start:r.start + r.length],
                headers={"Accept-Ranges": "bytes",
                         "Content-Range":
                             f"bytes {r.start}-{r.start + r.length - 1}/{len(BLOB)}"})
        return web.Response(body=BLOB, headers={"Accept-Ranges": "bytes"})

    app = web.Application()
    app.router.add_get(f"/v2/library/app/blobs/sha256:{BLOB_SHA}", blob)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0,
                       ssl_context=ca.server_context("127.0.0.1"))
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    return runner, port, stats


def _trust_ca_for_source_clients(ca: CertAuthority, tmp_path) -> None:
    """Point the default SSL trust store at the test CA so the daemon's
    back-to-source client accepts the fake registry's forged cert (real
    deployments set DRAGONFLY_SSL_CA_FILE (or the system trust store) the
    same way)."""
    bundle = tmp_path / "ca-bundle.pem"
    bundle.write_bytes(ca.ca_cert_pem)
    os.environ["DRAGONFLY_SSL_CA_FILE"] = str(bundle)


# -- certify ----------------------------------------------------------------

def test_forged_leaf_passes_hostname_verification(run_async, tmp_path):
    ca = shared_ca()

    async def run():
        async def hello(request):
            return web.Response(text="hi")

        app = web.Application()
        app.router.add_get("/", hello)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0,
                           ssl_context=ca.server_context("localhost"))
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        try:
            # Full hostname verification against the forged cert: connect
            # to 127.0.0.1 but verify as "localhost" (the SAN we forged).
            conn = aiohttp.TCPConnector(ssl=ca.trust_context(),
                                        resolver=None)
            async with aiohttp.ClientSession(connector=conn) as sess:
                async with sess.get(f"https://localhost:{port}/") as resp:
                    assert resp.status == 200
                    assert await resp.text() == "hi"
        finally:
            await runner.cleanup()

    run_async(run())


def test_ca_persistence_roundtrip(tmp_path):
    d = str(tmp_path / "ca")
    ca1 = CertAuthority.load_or_generate(persist_dir=d)
    ca2 = CertAuthority.load_or_generate(persist_dir=d)
    assert ca1.ca_cert_pem == ca2.ca_cert_pem  # same root across restarts
    assert (os.stat(os.path.join(d, "proxy-ca.key")).st_mode & 0o777) == 0o600


def test_parse_sni_from_real_clienthello():
    """parse_sni must decode the SNI from a ClientHello produced by the
    real ssl stack (MemoryBIO handshake, no sockets)."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_NONE
    inbio, outbio = ssl.MemoryBIO(), ssl.MemoryBIO()
    obj = ctx.wrap_bio(inbio, outbio, server_hostname="registry.example.com")
    try:
        obj.do_handshake()
    except ssl.SSLWantReadError:
        pass
    hello = outbio.read()
    assert parse_sni(hello) == "registry.example.com"
    assert parse_sni(b"\x17\x03\x03\x00\x05hello") is None
    assert parse_sni(b"") is None


# -- CONNECT hijack ---------------------------------------------------------

def test_connect_hijack_blob_rides_p2p(run_async, tmp_path):
    """An HTTPS blob pull through the proxy's CONNECT tunnel must be
    TLS-terminated and served from the P2P cache: the second pull may not
    touch the origin (a blind relay would hit it every time)."""
    ca = shared_ca()
    _trust_ca_for_source_clients(ca, tmp_path)

    async def run():
        runner, origin_port, stats = await start_tls_registry(ca)
        tm = make_task_manager(tmp_path)
        url = f"https://127.0.0.1:{origin_port}/v2/library/app/blobs/sha256:{BLOB_SHA}"
        proxy = Proxy(
            P2PTransport(tm, rules=[ProxyRule(regex=r"blobs/sha256.*")]),
            cert_authority=ca,
            white_list_ports=[],   # origin rides an ephemeral port
        )
        proxy_port = await proxy.serve("127.0.0.1", 0)
        try:
            async with aiohttp.ClientSession() as sess:
                for expect_origin_hits in (True, False):
                    before = stats["blob_gets"]
                    async with sess.get(
                            url, proxy=f"http://127.0.0.1:{proxy_port}",
                            ssl=ca.trust_context()) as resp:
                        assert resp.status == 200
                        body = await resp.read()
                    assert body == BLOB
                    if expect_origin_hits:
                        assert stats["blob_gets"] > before
                    else:
                        # Cache hit: hijacked + served from the piece store.
                        assert stats["blob_gets"] == before
        finally:
            await proxy.close()
            tm.storage.close()
            await runner.cleanup()

    run_async(run())


def test_connect_hijack_host_filter(run_async, tmp_path):
    """Hosts outside hijack_hosts keep the blind relay (end-to-end TLS to
    the origin, origin hit every time)."""
    ca = shared_ca()
    _trust_ca_for_source_clients(ca, tmp_path)

    async def run():
        runner, origin_port, stats = await start_tls_registry(ca)
        tm = make_task_manager(tmp_path)
        url = f"https://127.0.0.1:{origin_port}/v2/library/app/blobs/sha256:{BLOB_SHA}"
        proxy = Proxy(
            P2PTransport(tm, rules=[ProxyRule(regex=r"blobs/sha256.*")]),
            cert_authority=ca,
            hijack_hosts=[r"registry\.internal"],   # 127.0.0.1 not matched
            white_list_ports=[],
        )
        proxy_port = await proxy.serve("127.0.0.1", 0)
        try:
            async with aiohttp.ClientSession() as sess:
                for _ in range(2):
                    before = stats["blob_gets"]
                    async with sess.get(
                            url, proxy=f"http://127.0.0.1:{proxy_port}",
                            ssl=ca.trust_context()) as resp:
                        assert resp.status == 200
                        assert await resp.read() == BLOB
                    assert stats["blob_gets"] > before  # straight to origin
        finally:
            await proxy.close()
            tm.storage.close()
            await runner.cleanup()

    run_async(run())


# -- SNI listener -----------------------------------------------------------

def test_sni_hijack_serves_p2p(run_async, tmp_path):
    """Direct-TLS client (no CONNECT) against the SNI listener: TLS is
    terminated with a cert forged for the SNI name and the request rides
    the rule engine / P2P cache."""
    ca = shared_ca()
    _trust_ca_for_source_clients(ca, tmp_path)

    async def run():
        runner, origin_port, stats = await start_tls_registry(ca)
        tm = make_task_manager(tmp_path)
        path = f"/v2/library/app/blobs/sha256:{BLOB_SHA}"
        proxy = Proxy(
            P2PTransport(tm, rules=[ProxyRule(regex=r"blobs/sha256.*")]),
            cert_authority=ca,
        )
        sni_port = await proxy.serve_sni("127.0.0.1", 0, hijack=True)

        async def fetch_once() -> bytes:
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", sni_port, ssl=ca.trust_context(),
                server_hostname="localhost")
            # Host points at the real origin (the SNI listener stands in
            # for the registry vhost).
            writer.write((f"GET {path} HTTP/1.1\r\n"
                          f"Host: 127.0.0.1:{origin_port}\r\n"
                          "Connection: close\r\n\r\n").encode())
            await writer.drain()
            raw = await reader.read(-1)
            writer.close()
            head, _, body = raw.partition(b"\r\n\r\n")
            assert b" 200 " in head.split(b"\r\n", 1)[0]
            if b"chunked" in head.lower():
                out = bytearray()
                while body:
                    size_s, _, body = body.partition(b"\r\n")
                    size = int(size_s, 16)
                    if size == 0:
                        break
                    out += body[:size]
                    body = body[size + 2:]
                return bytes(out)
            return body

        try:
            assert await fetch_once() == BLOB
            before = stats["blob_gets"]
            assert await fetch_once() == BLOB
            assert stats["blob_gets"] == before   # second pull: cache
        finally:
            await proxy.close()
            tm.storage.close()
            await runner.cleanup()

    run_async(run())
