"""Scheduler churn/load: 200 concurrent registrants on one task.

VERDICT r1 weak #6: `_schedule_and_send` runs a patience loop per
registering peer; at fleet scale that is hundreds of concurrent retry
loops. This drives 200 simulated peers (fake announce streams, no real
daemons) through register → schedule → piece reports → finish, with a
slice of peers dying mid-download, and asserts: ~1 origin fetch, every
survivor finishes, and the event loop never stalls (scheduling stays
O(events), no busy spin). Models the v5p-256 fan-out (SURVEY §6 north
star) at unit-test scale.
"""

from __future__ import annotations

import asyncio
import random

from dragonfly2_tpu.scheduler.config import SchedulerConfig
from dragonfly2_tpu.scheduler.service import SchedulerService

N_PEERS = 200
N_DIE = 30          # peers that vanish mid-download
N_PIECES = 10
PIECE_SIZE = 1 << 20
CONTENT_LEN = N_PIECES * PIECE_SIZE


class FakeStream:
    """Duck-typed ServerStream: the scheduler sends into to_peer, the
    simulated peer feeds requests into to_sched."""

    def __init__(self, open_body):
        self.open_body = open_body
        self.to_sched: asyncio.Queue = asyncio.Queue()
        self.to_peer: asyncio.Queue = asyncio.Queue()

    async def send(self, body):
        await self.to_peer.put(body)

    async def recv(self, timeout=None):
        return await self.to_sched.get()


async def _serve(svc: SchedulerService, stream: FakeStream):
    try:
        await svc.announce_peer(stream, None)
    except Exception:
        pass


def _open_body(i: int) -> dict:
    return {
        "host": {"id": f"host-{i}", "hostname": f"h{i}", "ip": "10.0.0.1",
                 "port": 8000 + i, "upload_port": 9000 + i},
        "peer_id": f"peer-{i}",
        "task_id": "churn-task",
        "url": "http://origin/blob",
    }


def test_churn_200_peers_one_origin_fetch(run_async):
    async def body():
        rng = random.Random(7)
        cfg = SchedulerConfig()
        cfg.scheduling.retry_interval = 0.02
        # Patience must comfortably exceed the first finisher's wall time on
        # a loaded 1-core CI host, or waiting peers get spurious back-source
        # grants and the origin-economy assertion below flakes.
        cfg.scheduling.no_source_patience = 2.0
        cfg.seed_peer_enabled = False
        svc = SchedulerService(cfg)

        origin_fetches = 0
        finished: set[int] = set()
        max_lag = 0.0

        async def heartbeat():
            nonlocal max_lag
            loop = asyncio.get_running_loop()
            while True:
                t0 = loop.time()
                await asyncio.sleep(0.01)
                max_lag = max(max_lag, loop.time() - t0 - 0.01)

        async def peer(i: int):
            nonlocal origin_fetches
            stream = FakeStream(_open_body(i))
            server = asyncio.ensure_future(_serve(svc, stream))
            dies = i < N_DIE and i > 0
            try:
                await stream.to_sched.put({"type": "register"})
                msg = await asyncio.wait_for(stream.to_peer.get(), timeout=30)
                kind = msg.get("type")
                if kind == "need_back_source":
                    origin_fetches += 1
                elif kind == "small_task":
                    finished.add(i)
                    await stream.to_sched.put(
                        {"type": "download_finished",
                         "content_length": CONTENT_LEN,
                         "piece_size": PIECE_SIZE,
                         "total_piece_count": N_PIECES})
                    return
                elif kind != "normal_task":
                    raise AssertionError(f"peer {i} got {kind}: {msg}")

                await stream.to_sched.put({
                    "type": "download_started",
                    "content_length": CONTENT_LEN,
                    "piece_size": PIECE_SIZE,
                    "total_piece_count": N_PIECES})
                for n in range(N_PIECES):
                    if dies and n == N_PIECES // 2:
                        return  # vanish: stream reader sees close below
                    await asyncio.sleep(rng.uniform(0, 0.01))
                    await stream.to_sched.put({
                        "type": "piece_finished",
                        "piece": {"piece_num": n,
                                  "range_start": n * PIECE_SIZE,
                                  "range_size": PIECE_SIZE,
                                  "digest": "", "download_cost_ms": 5,
                                  "dst_peer_id": ""}})
                # A slice of survivors exercises the reschedule path first.
                if i % 10 == 5:
                    await stream.to_sched.put({"type": "reschedule",
                                               "blocklist": [],
                                               "description": "test churn"})
                    nxt = await asyncio.wait_for(stream.to_peer.get(),
                                                 timeout=30)
                    assert nxt.get("type") in ("normal_task",
                                               "need_back_source"), nxt
                    if nxt.get("type") == "need_back_source":
                        origin_fetches += 1
                await stream.to_sched.put({
                    "type": "download_finished",
                    "content_length": CONTENT_LEN,
                    "piece_size": PIECE_SIZE,
                    "total_piece_count": N_PIECES})
                finished.add(i)
            finally:
                await stream.to_sched.put(None)  # client half-close
                await asyncio.wait_for(server, timeout=30)

        hb = asyncio.ensure_future(heartbeat())
        try:
            # Staggered arrival storm: all 200 within ~0.5 s.
            async def delayed(i):
                await asyncio.sleep(rng.uniform(0, 0.5))
                await peer(i)

            await asyncio.wait_for(
                asyncio.gather(*[delayed(i) for i in range(N_PEERS)]),
                timeout=90)
        finally:
            hb.cancel()

        survivors = N_PEERS - (N_DIE - 1)   # peer 0 never dies
        assert len(finished) == survivors, (len(finished), survivors)
        # Origin economy: the first peer + at most a couple of reschedule
        # demotions while the DAG warms up.
        assert origin_fetches <= 3, origin_fetches
        # The event loop stayed responsive through the storm.
        assert max_lag < 0.25, f"event loop stalled {max_lag * 1000:.0f} ms"
        # All dead peers were cleaned off the DAG (stream-gone handling).
        task = svc.tasks.load("churn-task")
        gone = [p for p in task.peers() if p.id in
                {f"peer-{i}" for i in range(1, N_DIE)}]
        assert all(p.state in ("failed", "leave") for p in gone), \
            [(p.id, p.state) for p in gone][:5]

    run_async(body(), timeout=120)
