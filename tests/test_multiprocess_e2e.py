"""Hermetic multi-process E2E: real CLI processes on localhost.

SURVEY §4's kind-replacement harness: one scheduler process, one seed
daemon, N peer daemons — all spawned as ``python -m dragonfly2_tpu.cli.main``
subprocesses against an in-test origin. Verification mirrors
test/e2e/v2/dfget_test.go: sha256 of every output AND of the piece store on
the client + seed by task ID.

Marked ``slow``-ish (process spawns); kept to one scenario battery so the
suite stays CI-friendly.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import glob
import os
import random
import signal
import socket
import subprocess
import sys
import time

import pytest
from aiohttp import web

from dragonfly2_tpu.pkg.piece import Range

CONTENT = bytes(random.Random(77).randbytes(24 * 1024 * 1024))
SHA = hashlib.sha256(CONTENT).hexdigest()


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


async def _start_origin():
    stats = {"streams": 0, "bytes": 0}

    async def blob(request: web.Request) -> web.Response:
        stats["streams"] += 1
        rng = request.headers.get("Range")
        if rng:
            r = Range.parse_http(rng, len(CONTENT))
            data = CONTENT[r.start:r.start + r.length]
            stats["bytes"] += len(data)
            return web.Response(status=206, body=data, headers={
                "Accept-Ranges": "bytes",
                "Content-Range":
                    f"bytes {r.start}-{r.start + r.length - 1}/{len(CONTENT)}"})
        stats["bytes"] += len(CONTENT)
        return web.Response(body=CONTENT, headers={"Accept-Ranges": "bytes"})

    app = web.Application()
    app.router.add_get("/model.bin", blob)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    return runner, site._server.sockets[0].getsockname()[1], stats


def _spawn(args: list[str], log_path: str) -> subprocess.Popen:
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # Child processes must not inherit the test's virtual-device JAX setup
    # (8 CPU devices per daemon = needless threads/memory in an E2E).
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    logf = open(log_path, "w")
    return subprocess.Popen(
        [sys.executable, "-m", "dragonfly2_tpu.cli.main", *args],
        stdout=logf, stderr=subprocess.STDOUT, env=env)


def _wait_sock(path: str, timeout: float = 90.0) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            return True
        time.sleep(0.1)
    return False


def _store_sha_by_task(work_home: str, task_id: str) -> str | None:
    """sha256 of the piece store's data file for a task (e2e/v2
    util/task.go CalculateSha256ByTaskID analog)."""
    for meta_path in glob.glob(f"{work_home}/**/metadata.json", recursive=True):
        with open(meta_path) as f:
            meta = json.load(f)
        if meta["task_id"] == task_id and meta.get("done"):
            data = os.path.join(os.path.dirname(meta_path), "data")
            h = hashlib.sha256()
            with open(data, "rb") as df:
                while True:
                    chunk = df.read(1 << 20)
                    if not chunk:
                        break
                    h.update(chunk)
            return h.hexdigest()
    return None


def test_multiprocess_fanout(run_async, tmp_path):
    """scheduler + seed + 2 peer daemon PROCESSES; dfget from both peers:
    outputs sha-verify, stores sha-verify on every node, origin served ~one
    copy through the seed."""

    async def run():
        runner, origin_port, stats = await _start_origin()
        sched_port = _free_port()
        procs: list[subprocess.Popen] = []
        homes = {name: str(tmp_path / name) for name in ("seed", "p1", "p2")}
        try:
            procs.append(_spawn(
                ["scheduler", "--host", "127.0.0.1", "--port", str(sched_port)],
                str(tmp_path / "sched.log")))
            await asyncio.sleep(0)
            procs.append(_spawn(
                ["daemon", "--work-home", homes["seed"], "--seed-peer",
                 "--scheduler", f"127.0.0.1:{sched_port}"],
                str(tmp_path / "seed.log")))
            procs.append(_spawn(
                ["daemon", "--work-home", homes["p1"],
                 "--scheduler", f"127.0.0.1:{sched_port}"],
                str(tmp_path / "p1.log")))
            procs.append(_spawn(
                ["daemon", "--work-home", homes["p2"],
                 "--scheduler", f"127.0.0.1:{sched_port}"],
                str(tmp_path / "p2.log")))
            for name in homes:
                ok = await asyncio.to_thread(
                    _wait_sock, f"{homes[name]}/run/dfdaemon.sock")
                assert ok, open(tmp_path / f"{name}.log").read()[-2000:]

            url = f"http://127.0.0.1:{origin_port}/model.bin"

            def dfget(home: str, out: str) -> subprocess.Popen:
                return _spawn(
                    ["dfget", url, "-O", out, "--work-home", home,
                     "--no-daemon", "--digest", f"sha256:{SHA}"],
                    out + ".log")

            outs = [str(tmp_path / "out1.bin"), str(tmp_path / "out2.bin")]
            downloads = [dfget(homes["p1"], outs[0]),
                         dfget(homes["p2"], outs[1])]
            # Wait OFF the event loop: the origin server lives in this test
            # process, so a blocking Popen.wait would starve it.
            for p, out in zip(downloads, outs):
                rc = await asyncio.to_thread(p.wait, 120)
                assert rc == 0, open(out + ".log").read()[-2000:]

            # Output integrity on both clients (dfget_test.go:26-76 style).
            for out in outs:
                with open(out, "rb") as f:
                    assert hashlib.sha256(f.read()).hexdigest() == SHA

            # Store integrity by task id on every node incl. the seed.
            task_id = None
            for meta_path in glob.glob(f"{homes['p1']}/**/metadata.json",
                                       recursive=True):
                task_id = json.load(open(meta_path))["task_id"]
            assert task_id
            for name, home in homes.items():
                assert _store_sha_by_task(home, task_id) == SHA, name

            # Origin bandwidth: the seed's fetch only (≲1.5 copies allows
            # ranged back-source groups).
            assert stats["bytes"] <= int(len(CONTENT) * 1.5), stats
        finally:
            for p in procs:
                p.send_signal(signal.SIGTERM)
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
            await runner.cleanup()

    run_async(run())
