"""Hermetic multi-process E2E: real CLI processes on localhost.

SURVEY §4's kind-replacement harness: one scheduler process, one seed
daemon, N peer daemons — all spawned as ``python -m dragonfly2_tpu.cli.main``
subprocesses against an in-test origin. Verification mirrors
test/e2e/v2/dfget_test.go: sha256 of every output AND of the piece store on
the client + seed by task ID.

Marked ``slow``-ish (process spawns); kept to one scenario battery so the
suite stays CI-friendly.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import glob
import os
import random
import signal
import socket
import subprocess
import sys
import time

import pytest
from aiohttp import web

from dragonfly2_tpu.pkg.hermetic import scrub_accelerator_env
from dragonfly2_tpu.pkg.piece import Range

CONTENT = bytes(random.Random(77).randbytes(24 * 1024 * 1024))
SHA = hashlib.sha256(CONTENT).hexdigest()


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


async def _start_origin():
    stats = {"streams": 0, "bytes": 0}

    async def blob(request: web.Request) -> web.Response:
        stats["streams"] += 1
        rng = request.headers.get("Range")
        if rng:
            r = Range.parse_http(rng, len(CONTENT))
            data = CONTENT[r.start:r.start + r.length]
            stats["bytes"] += len(data)
            return web.Response(status=206, body=data, headers={
                "Accept-Ranges": "bytes",
                "Content-Range":
                    f"bytes {r.start}-{r.start + r.length - 1}/{len(CONTENT)}"})
        stats["bytes"] += len(CONTENT)
        return web.Response(body=CONTENT, headers={"Accept-Ranges": "bytes"})

    app = web.Application()
    app.router.add_get("/model.bin", blob)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    return runner, site._server.sockets[0].getsockname()[1], stats


def _spawn(args: list[str], log_path: str,
           jax_cpu: bool = False) -> subprocess.Popen:
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # Child processes must not inherit the test's virtual-device JAX setup
    # (8 CPU devices per daemon = needless threads/memory in an E2E).
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    if jax_cpu:
        # Device-sink daemon: single-device CPU jax backend, with the
        # sandbox's accelerator-plugin triggers scrubbed (they dial a TPU
        # relay — see pkg/hermetic.py).
        env["JAX_PLATFORMS"] = "cpu"
        scrub_accelerator_env(env)
    logf = open(log_path, "w")
    return subprocess.Popen(
        [sys.executable, "-m", "dragonfly2_tpu.cli.main", *args],
        stdout=logf, stderr=subprocess.STDOUT, env=env)


def _wait_sock(path: str, timeout: float = 90.0) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            return True
        time.sleep(0.1)
    return False


def _store_sha_by_task(work_home: str, task_id: str) -> str | None:
    """sha256 of the piece store's data file for a task (e2e/v2
    util/task.go CalculateSha256ByTaskID analog)."""
    for meta_path in glob.glob(f"{work_home}/**/metadata.json", recursive=True):
        with open(meta_path) as f:
            meta = json.load(f)
        if meta["task_id"] == task_id and meta.get("done"):
            data = os.path.join(os.path.dirname(meta_path), "data")
            h = hashlib.sha256()
            with open(data, "rb") as df:
                while True:
                    chunk = df.read(1 << 20)
                    if not chunk:
                        break
                    h.update(chunk)
            return h.hexdigest()
    return None


class _Fabric:
    """Spawn/teardown helper for real-process scenarios: scheduler + seed +
    N peers as CLI subprocesses, with exit-code collection on teardown
    (the reference e2e's pod-restart-count analog:
    /root/reference/test/e2e/e2e_test.go:34-75)."""

    def __init__(self, tmp_path, peers=("p1", "p2"), seed_yaml: str = ""):
        self.tmp = tmp_path
        self.peer_names = list(peers)
        self.seed_yaml = seed_yaml
        self.procs: dict[str, subprocess.Popen] = {}
        self.homes: dict[str, str] = {}
        self.exit_codes: dict[str, int] = {}
        self.sched_port = 0

    async def start(self, extra_daemon_args: dict | None = None,
                    extra_scheduler_args: list[str] | None = None) -> None:
        extra = extra_daemon_args or {}
        self.sched_port = _free_port()
        self.procs["sched"] = _spawn(
            ["scheduler", "--host", "127.0.0.1",
             "--port", str(self.sched_port),
             *(extra_scheduler_args or [])],
            str(self.tmp / "sched.log"))
        names = ["seed"] + self.peer_names
        for name in names:
            home = str(self.tmp / name)
            self.homes[name] = home
            args = ["daemon", "--work-home", home,
                    "--scheduler", f"127.0.0.1:{self.sched_port}"]
            if name == "seed":
                args.append("--seed-peer")
                if self.seed_yaml:
                    cfg_path = str(self.tmp / "seed_cfg.yaml")
                    with open(cfg_path, "w") as f:
                        f.write(self.seed_yaml)
                    args += ["--config", cfg_path]
            args += extra.get(name, [])
            self.procs[name] = _spawn(args, str(self.tmp / f"{name}.log"))
        for name in names:
            ok = await asyncio.to_thread(
                _wait_sock, f"{self.homes[name]}/run/dfdaemon.sock")
            assert ok, self.log_tail(name)

    def log_tail(self, name: str, n: int = 2000) -> str:
        try:
            return open(self.tmp / f"{name}.log").read()[-n:]
        except OSError:
            return "<no log>"

    def kill(self, name: str, sig=signal.SIGKILL) -> None:
        self.procs[name].send_signal(sig)
        self.exit_codes[name] = self.procs[name].wait(timeout=15)

    async def restart_daemon(self, name: str) -> None:
        """SIGTERM + respawn on the same work home (store reload path)."""
        if self.procs[name].poll() is None:
            self.procs[name].send_signal(signal.SIGTERM)
        self.exit_codes[name] = await asyncio.to_thread(
            self.procs[name].wait, 20)
        # A fresh-spawn readiness check needs the stale socket gone (the
        # daemon usually unlinks it on clean exit; tolerate either).
        try:
            os.remove(f"{self.homes[name]}/run/dfdaemon.sock")
        except FileNotFoundError:
            pass
        args = ["daemon", "--work-home", self.homes[name],
                "--scheduler", f"127.0.0.1:{self.sched_port}"]
        if name == "seed":
            args.append("--seed-peer")
        self.procs[name] = _spawn(args, str(self.tmp / f"{name}.restart.log"))
        ok = await asyncio.to_thread(
            _wait_sock, f"{self.homes[name]}/run/dfdaemon.sock")
        assert ok, self.log_tail(name)

    def dfget(self, name: str, url: str, out: str,
              extra: list[str] | None = None,
              with_digest: bool = True) -> subprocess.Popen:
        # with_digest=False: the task id must match digestless meta (e.g.
        # a preheat-warmed task — digest is part of the id, reference
        # pkg/idgen/task_id.go:65); integrity still holds via the piece
        # chain, and callers sha-verify the output themselves.
        digest = ["--digest", f"sha256:{SHA}"] if with_digest else []
        return _spawn(
            ["dfget", url, "-O", out, "--work-home", self.homes[name],
             "--no-daemon", *digest, *(extra or [])],
            out + ".log")

    async def await_dfget(self, proc: subprocess.Popen, out: str,
                          timeout: float = 120) -> None:
        rc = await asyncio.to_thread(proc.wait, timeout)
        assert rc == 0, open(out + ".log").read()[-2000:]
        with open(out, "rb") as f:
            assert hashlib.sha256(f.read()).hexdigest() == SHA

    async def teardown(self) -> None:
        for name, p in self.procs.items():
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for name, p in self.procs.items():
            try:
                self.exit_codes.setdefault(name, p.wait(timeout=10))
            except subprocess.TimeoutExpired:
                p.kill()
                self.exit_codes[name] = p.wait()


def _wait_first_piece(homes: list[str], timeout: float = 60.0) -> bool:
    """Block until any task data file under any home has bytes — the
    'transfer is mid-flight' trigger for kill scenarios."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for home in homes:
            for data in glob.glob(f"{home}/**/data", recursive=True):
                try:
                    if os.path.getsize(data) > 0:
                        return True
                except OSError:
                    pass
        time.sleep(0.05)
    return False


def test_multiprocess_fanout(run_async, tmp_path):
    """scheduler + seed + 2 peer daemon PROCESSES; dfget from both peers:
    outputs sha-verify, stores sha-verify on every node, origin served ~one
    copy through the seed."""

    async def run():
        runner, origin_port, stats = await _start_origin()
        sched_port = _free_port()
        procs: list[subprocess.Popen] = []
        homes = {name: str(tmp_path / name) for name in ("seed", "p1", "p2")}
        try:
            procs.append(_spawn(
                ["scheduler", "--host", "127.0.0.1", "--port", str(sched_port)],
                str(tmp_path / "sched.log")))
            await asyncio.sleep(0)
            procs.append(_spawn(
                ["daemon", "--work-home", homes["seed"], "--seed-peer",
                 "--scheduler", f"127.0.0.1:{sched_port}"],
                str(tmp_path / "seed.log")))
            procs.append(_spawn(
                ["daemon", "--work-home", homes["p1"],
                 "--scheduler", f"127.0.0.1:{sched_port}"],
                str(tmp_path / "p1.log")))
            procs.append(_spawn(
                ["daemon", "--work-home", homes["p2"],
                 "--scheduler", f"127.0.0.1:{sched_port}"],
                str(tmp_path / "p2.log")))
            for name in homes:
                ok = await asyncio.to_thread(
                    _wait_sock, f"{homes[name]}/run/dfdaemon.sock")
                assert ok, open(tmp_path / f"{name}.log").read()[-2000:]

            url = f"http://127.0.0.1:{origin_port}/model.bin"

            def dfget(home: str, out: str) -> subprocess.Popen:
                return _spawn(
                    ["dfget", url, "-O", out, "--work-home", home,
                     "--no-daemon", "--digest", f"sha256:{SHA}"],
                    out + ".log")

            outs = [str(tmp_path / "out1.bin"), str(tmp_path / "out2.bin")]
            downloads = [dfget(homes["p1"], outs[0]),
                         dfget(homes["p2"], outs[1])]
            # Wait OFF the event loop: the origin server lives in this test
            # process, so a blocking Popen.wait would starve it.
            for p, out in zip(downloads, outs):
                rc = await asyncio.to_thread(p.wait, 120)
                assert rc == 0, open(out + ".log").read()[-2000:]

            # Output integrity on both clients (dfget_test.go:26-76 style).
            for out in outs:
                with open(out, "rb") as f:
                    assert hashlib.sha256(f.read()).hexdigest() == SHA

            # Store integrity by task id on every node incl. the seed.
            task_id = None
            for meta_path in glob.glob(f"{homes['p1']}/**/metadata.json",
                                       recursive=True):
                task_id = json.load(open(meta_path))["task_id"]
            assert task_id
            for name, home in homes.items():
                assert _store_sha_by_task(home, task_id) == SHA, name

            # Origin bandwidth: the seed's fetch only (≲1.5 copies allows
            # ranged back-source groups).
            assert stats["bytes"] <= int(len(CONTENT) * 1.5), stats
        finally:
            for p in procs:
                p.send_signal(signal.SIGTERM)
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
            await runner.cleanup()

    run_async(run())


def test_dfget_cold_host_auto_spawn_joins_p2p(run_async, tmp_path):
    """A COLD host (no daemon running, empty work-home) runs plain dfget
    with --scheduler: the CLI health-checks the socket, forks a daemon
    wired to the scheduler, waits for the handshake, and the download
    rides P2P (p2p=True) off the seed — mirroring
    cmd/dfget/cmd/root.go:251-340 where dfget spawns dfdaemon on demand.
    Direct-source remains the final fallback but must NOT be what happens
    here."""

    async def run():
        runner, origin_port, stats = await _start_origin()
        fab = _Fabric(tmp_path, peers=())
        spawned_home = str(tmp_path / "coldhost")
        try:
            await fab.start()   # scheduler + seed only
            url = f"http://127.0.0.1:{origin_port}/model.bin"
            # Warm the seed so the cold host's pull is served P2P.
            warm = str(tmp_path / "warm.bin")
            await fab.await_dfget(fab.dfget("seed", url, warm,
                                            with_digest=False), warm)
            bytes_warm = stats["bytes"]

            out = str(tmp_path / "cold.bin")
            p = _spawn(
                ["dfget", url, "-O", out, "--work-home", spawned_home,
                 "--scheduler", f"127.0.0.1:{fab.sched_port}"],
                out + ".log")
            rc = await asyncio.to_thread(p.wait, 120)
            log_text = open(out + ".log").read()
            assert rc == 0, log_text[-2000:]
            with open(out, "rb") as f:
                assert hashlib.sha256(f.read()).hexdigest() == SHA
            assert "spawned daemon" in log_text, log_text[-1000:]
            assert "p2p=True" in log_text, log_text[-1000:]
            # Pure P2P: the cold host's pull added no origin traffic.
            assert stats["bytes"] == bytes_warm, stats
        finally:
            subprocess.run(["pkill", "-f", spawned_home],
                           capture_output=True)
            await fab.teardown()
            await runner.cleanup()

    run_async(run(), timeout=240)


def test_multiprocess_seed_death(run_async, tmp_path):
    """SIGKILL the seed PROCESS mid-transfer: both peers still land
    sha-exact (reschedule onto each other + bounded back-source), and the
    collected exit code proves the kill was a real process death."""

    async def run():
        runner, origin_port, stats = await _start_origin()
        # Rate-limit seed serving so the kill lands mid-transfer.
        fab = _Fabric(tmp_path, seed_yaml="upload:\n  rate_limit: 4194304\n")
        try:
            await fab.start()
            url = f"http://127.0.0.1:{origin_port}/model.bin"
            outs = [str(tmp_path / "o1.bin"), str(tmp_path / "o2.bin")]
            dls = [fab.dfget("p1", url, outs[0]),
                   fab.dfget("p2", url, outs[1])]

            hit = await asyncio.to_thread(
                _wait_first_piece, [fab.homes["p1"], fab.homes["p2"]])
            assert hit, "no piece landed on any peer before timeout"
            await asyncio.to_thread(fab.kill, "seed", signal.SIGKILL)
            assert fab.exit_codes["seed"] == -signal.SIGKILL

            for p, out in zip(dls, outs):
                await fab.await_dfget(p, out)
            # Bounded origin re-touch: seed's partial + ≤1 remainder/peer.
            assert stats["bytes"] <= 3 * len(CONTENT) + (1 << 20), stats
        finally:
            await fab.teardown()
            await runner.cleanup()

    run_async(run(), timeout=240)


def test_multiprocess_scheduler_death(run_async, tmp_path):
    """SIGKILL the scheduler PROCESS mid-transfer: with source fallback
    permitted the in-flight download still lands sha-exact (conductor
    demotion), and a FRESH dfget after the death also lands (registration
    ring failover → back-source demotion)."""

    async def run():
        runner, origin_port, stats = await _start_origin()
        fab = _Fabric(tmp_path, peers=("p1",),
                      seed_yaml="upload:\n  rate_limit: 4194304\n")
        try:
            await fab.start()
            url = f"http://127.0.0.1:{origin_port}/model.bin"
            out1 = str(tmp_path / "s1.bin")
            dl = fab.dfget("p1", url, out1)
            hit = await asyncio.to_thread(
                _wait_first_piece, [fab.homes["p1"]])
            assert hit, "no piece landed before timeout"
            await asyncio.to_thread(fab.kill, "sched", signal.SIGKILL)
            assert fab.exit_codes["sched"] == -signal.SIGKILL
            await fab.await_dfget(dl, out1)

            # Schedulerless cold task: a DIFFERENT task id (range variant)
            # from the same daemon must still land via demotion.
            out2 = str(tmp_path / "s2.bin")
            p = _spawn(["dfget", url, "-O", out2,
                        "--work-home", fab.homes["p1"], "--no-daemon",
                        "--range", "0-1048575"], out2 + ".log")
            rc = await asyncio.to_thread(p.wait, 120)
            assert rc == 0, open(out2 + ".log").read()[-2000:]
            with open(out2, "rb") as f:
                got = f.read()
            assert got == CONTENT[:1048576]
        finally:
            await fab.teardown()
            await runner.cleanup()

    run_async(run(), timeout=240)


def test_multiprocess_daemon_restart_reuse(run_async, tmp_path):
    """Restart a peer daemon PROCESS after a download: clean SIGTERM exit
    (code 0 — restart-count hygiene), store reloads from disk, and a second
    dfget is a warm reuse that never touches the origin again."""

    async def run():
        runner, origin_port, stats = await _start_origin()
        fab = _Fabric(tmp_path, peers=("p1",))
        try:
            await fab.start()
            url = f"http://127.0.0.1:{origin_port}/model.bin"
            out1 = str(tmp_path / "r1.bin")
            await fab.await_dfget(fab.dfget("p1", url, out1), out1)
            bytes_before = stats["bytes"]

            await fab.restart_daemon("p1")
            assert fab.exit_codes["p1"] == 0, \
                f"daemon SIGTERM exit {fab.exit_codes['p1']}"

            out2 = str(tmp_path / "r2.bin")
            await fab.await_dfget(fab.dfget("p1", url, out2), out2)
            assert stats["bytes"] == bytes_before, \
                "reuse after restart must not re-touch the origin"
            assert "reuse=True" in open(out2 + ".log").read()
        finally:
            await fab.teardown()
            await runner.cleanup()

    run_async(run(), timeout=240)


def test_multiprocess_device_sink(run_async, tmp_path):
    """A peer daemon PROCESS with a CPU-backend jax device sink: dfget
    --device tpu lands the bytes on disk (sha-exact) AND in the daemon's
    device Array, reported as device_verified; warm reuse re-finalizes
    the sink without touching the origin."""

    async def run():
        runner, origin_port, stats = await _start_origin()
        fab = _Fabric(tmp_path, peers=())
        try:
            await fab.start()
            home = str(tmp_path / "dp")
            fab.homes["dp"] = home
            fab.procs["dp"] = _spawn(
                ["daemon", "--work-home", home, "--device-sink",
                 "--scheduler", f"127.0.0.1:{fab.sched_port}"],
                str(tmp_path / "dp.log"), jax_cpu=True)
            ok = await asyncio.to_thread(_wait_sock, f"{home}/run/dfdaemon.sock")
            assert ok, fab.log_tail("dp")

            url = f"http://127.0.0.1:{origin_port}/model.bin"
            out1 = str(tmp_path / "d1.bin")
            p = _spawn(["dfget", url, "-O", out1, "--work-home", home,
                        "--no-daemon", "--device", "tpu",
                        "--digest", f"sha256:{SHA}"], out1 + ".log")
            await fab.await_dfget(p, out1, timeout=180)
            log1 = open(out1 + ".log").read()
            assert "device_verified=True" in log1, log1[-800:]
            bytes_cold = stats["bytes"]

            # Warm: reuse must re-finalize the sink, origin untouched.
            out2 = str(tmp_path / "d2.bin")
            p = _spawn(["dfget", url, "-O", out2, "--work-home", home,
                        "--no-daemon", "--device", "tpu",
                        "--digest", f"sha256:{SHA}"], out2 + ".log")
            await fab.await_dfget(p, out2, timeout=120)
            log2 = open(out2 + ".log").read()
            assert "reuse=True" in log2, log2[-800:]
            assert "device_verified=True" in log2, log2[-800:]
            assert stats["bytes"] == bytes_cold
        finally:
            await fab.teardown()
            await runner.cleanup()

    run_async(run(), timeout=300)


def test_multiprocess_manager_preheat(run_async, tmp_path):
    """The full preheat call stack across real PROCESSES (SURVEY §3.4):
    manager REST job -> manager drpc queue -> scheduler job worker ->
    seed-task trigger -> seed daemon back-sources -> store sha-exact.
    Afterwards a peer dfget rides pure P2P: the origin byte count must
    not grow. Reference posture: test/e2e + manager preheat handlers
    (/root/reference/manager/job/preheat.go, scheduler/job/job.go)."""

    async def run():
        from aiohttp import ClientSession

        runner, origin_port, stats = await _start_origin()
        rest_port, drpc_port = _free_port(), _free_port()
        fab = _Fabric(tmp_path, peers=("p1",))
        mgr = _spawn(
            ["manager", "--host", "127.0.0.1", "--port", str(rest_port),
             "--grpc-port", str(drpc_port),
             "--db", str(tmp_path / "manager.db")],
            str(tmp_path / "manager.log"))
        fab.procs["manager"] = mgr
        base = f"http://127.0.0.1:{rest_port}"
        try:
            async with ClientSession() as http:
                for _ in range(300):
                    try:
                        async with http.get(f"{base}/healthy") as r:
                            if r.status == 200:
                                break
                    except Exception:
                        pass
                    await asyncio.sleep(0.1)
                else:
                    raise AssertionError(
                        "manager never healthy: " + fab.log_tail("manager"))

                # Scheduler AFTER the manager: it registers over drpc and
                # its job worker long-polls the cluster queue.
                await fab.start(extra_scheduler_args=[
                    "--manager", f"127.0.0.1:{drpc_port}"])
                url = f"http://127.0.0.1:{origin_port}/model.bin"

                async with http.post(
                        f"{base}/api/v1/users/signin",
                        json={"name": "root", "password": "dragonfly"}) as r:
                    assert r.status == 200, await r.text()
                    hdr = {"Authorization":
                           f"Bearer {(await r.json())['token']}"}
                async with http.post(
                        f"{base}/api/v1/jobs", headers=hdr,
                        json={"type": "preheat",
                              "args": {"type": "file", "url": url}}) as r:
                    assert r.status == 200, await r.text()
                    job_id = (await r.json())["id"]

                state = "PENDING"
                for _ in range(600):
                    async with http.get(f"{base}/api/v1/jobs/{job_id}",
                                        headers=hdr) as r:
                        state = (await r.json())["state"]
                    if state in ("SUCCESS", "FAILURE"):
                        break
                    await asyncio.sleep(0.2)
                assert state == "SUCCESS", (
                    state, fab.log_tail("sched"), fab.log_tail("seed"))

            # The preheat landed on the seed: a done store, sha-exact.
            task_id = None
            for meta_path in glob.glob(
                    f"{fab.homes['seed']}/**/metadata.json", recursive=True):
                meta = json.load(open(meta_path))
                if meta.get("done"):
                    task_id = meta["task_id"]
            assert task_id, fab.log_tail("seed")
            assert _store_sha_by_task(fab.homes["seed"], task_id) == SHA
            bytes_after_preheat = stats["bytes"]
            assert bytes_after_preheat <= int(len(CONTENT) * 1.5), stats

            # A peer pull after the preheat is pure P2P: origin untouched.
            # Digestless meta so the task id matches the preheat's
            # (a digest-pinned request is a DISTINCT task by design —
            # reference pkg/idgen/task_id.go:65).
            out = str(tmp_path / "warm.bin")
            p = fab.dfget("p1", url, out, with_digest=False)
            await fab.await_dfget(p, out, timeout=120)
            assert stats["bytes"] == bytes_after_preheat, stats
        finally:
            await fab.teardown()
            await runner.cleanup()

    run_async(run(), timeout=300)


def test_multiprocess_ici_slice_affinity(run_async, tmp_path):
    """Four peer daemons in two labeled slices + a seed: the scheduler's
    parent_picks counter (scraped from its real /metrics endpoint) must
    record intra-slice handouts — the ICI-lexicographic ranking and the
    warming-relay rule working across real process boundaries, not a sim.
    Every output stays sha-exact and the origin serves ~one copy."""

    async def run():
        import aiohttp

        runner, origin_port, stats = await _start_origin()
        metrics_port = _free_port()
        fab = _Fabric(tmp_path, peers=("p1", "p2", "p3", "p4"),
                      # Rate-limit the seed so transfers overlap: peers
                      # must find each other (and their slice-mates) as
                      # parents rather than all riding the seed.
                      seed_yaml="upload:\n  rate_limit: 16777216\n")
        try:
            await fab.start(
                extra_daemon_args={
                    "seed": ["--tpu-slice", "slice-seed"],
                    "p1": ["--tpu-slice", "slice-a", "--tpu-worker-index", "0"],
                    "p2": ["--tpu-slice", "slice-a", "--tpu-worker-index", "1"],
                    "p3": ["--tpu-slice", "slice-b", "--tpu-worker-index", "0"],
                    "p4": ["--tpu-slice", "slice-b", "--tpu-worker-index", "1"],
                },
                extra_scheduler_args=["--metrics-port", str(metrics_port)])
            url = f"http://127.0.0.1:{origin_port}/model.bin"
            outs = {n: str(tmp_path / f"{n}.bin")
                    for n in ("p1", "p2", "p3", "p4")}
            dls = {n: fab.dfget(n, url, out) for n, out in outs.items()}
            for n, p in dls.items():
                await fab.await_dfget(p, outs[n])

            from dragonfly2_tpu.pkg.metrics import parse_labeled_samples

            picks = {"intra": 0, "cross": 0, "unlabeled": 0}
            async with aiohttp.ClientSession() as s:
                async with s.get(
                        f"http://127.0.0.1:{metrics_port}/metrics",
                        timeout=aiohttp.ClientTimeout(total=10)) as resp:
                    assert resp.status == 200
                    body = await resp.text()
            picks.update(parse_labeled_samples(
                body, "dragonfly_tpu_scheduler_parent_picks_total",
                "locality"))
            # Every daemon carries a slice label, so no handout may be
            # unlabeled; and with two 2-peer slices pulling concurrently
            # at a throttled seed, at least one intra-slice handout must
            # occur (the pairs discover each other).
            assert picks["unlabeled"] == 0, picks
            assert picks["intra"] >= 1, picks
            assert picks["cross"] >= 1, picks  # seed ingress is cross
            # Origin economy holds under the slice labels.
            assert stats["bytes"] <= int(len(CONTENT) * 1.5), stats
        finally:
            await fab.teardown()
            await runner.cleanup()

    run_async(run(), timeout=240)
