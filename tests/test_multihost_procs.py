"""parallel/multihost across ≥2 REAL processes.

The single-process tests prove the API; a v5p pod runs N processes over one
global device set, and `jax.distributed` behaves differently there (device
visibility, process_index, cross-process array stitching). This spawns two
CPU processes — each playing one "host" that landed its own byte range —
initializes jax.distributed between them, stitches
``global_from_local_shards``, and asserts the assembled Array equals the
concatenated per-process landings (verified in every process via a psum
fingerprint, since no single process holds all shards addressably).

Skipped only when the runtime can't spawn subprocesses.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys

import pytest

from dragonfly2_tpu.pkg.hermetic import scrub_accelerator_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = r"""
import os, sys
sys.path.insert(0, os.environ["DF_REPO"])

import numpy as np
import jax

from dragonfly2_tpu.parallel import multihost

pid = int(os.environ["DF_PROC_ID"])
nprocs = int(os.environ["DF_NUM_PROCS"])

multihost.initialize_distributed(
    coordinator_address=os.environ["DF_COORD"],
    num_processes=nprocs, process_id=pid)
assert jax.process_count() == nprocs, jax.process_count()
assert jax.process_index() == pid

from jax.sharding import Mesh, PartitionSpec as P

devices = np.array(jax.devices())          # global: both processes' devices
n = devices.size
mesh = Mesh(devices.reshape(n), ("d",))

# Each "host" landed its own contiguous byte range: rows are globally
# numbered so equality against the concatenation is checkable anywhere.
rows_per_proc = (n // nprocs) * 2          # 2 rows per local device
cols = 8
base = pid * rows_per_proc
local = (np.arange(rows_per_proc * cols, dtype=np.float32)
         .reshape(rows_per_proc, cols) + base * cols)

arr = multihost.global_from_local_shards(mesh, local, axis_name="d")
assert arr.shape == (rows_per_proc * nprocs, cols), arr.shape

# Global verification without materializing remote shards: the sum of the
# assembled Array (an XLA cross-process reduction) must equal the sum of
# the full concatenation, and a weighted sum pins each row to its slot.
total_rows = rows_per_proc * nprocs
want = (np.arange(total_rows * cols, dtype=np.float64)
        .reshape(total_rows, cols))
weights = np.linspace(1.0, 2.0, total_rows, dtype=np.float64)[:, None]

got_sum = float(jax.jit(lambda a: a.astype("float64").sum())(arr))
assert abs(got_sum - want.sum()) < 1e-6, (got_sum, want.sum())
got_w = float(jax.jit(
    lambda a: (a.astype("float64") * weights).sum())(arr))
assert abs(got_w - (want * weights).sum()) < 1e-3, (got_w,)

# Local shards really live on this process's devices with the right data.
for shard in arr.addressable_shards:
    lo = shard.index[0].start or 0
    np.testing.assert_array_equal(
        np.asarray(shard.data),
        want[lo:lo + shard.data.shape[0]].astype(np.float32))

print(f"MULTIHOST_OK p{pid}")
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_global_assembly(tmp_path):
    nprocs = 2
    coord = f"127.0.0.1:{_free_port()}"
    procs = []
    for pid in range(nprocs):
        env = dict(os.environ)
        env.update({
            "DF_REPO": REPO,
            "DF_COORD": coord,
            "DF_PROC_ID": str(pid),
            "DF_NUM_PROCS": str(nprocs),
            "JAX_PLATFORMS": "cpu",
            # 2 local devices per process → 4 global.
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        })
        # The sandbox sitecustomize dials an accelerator relay when this
        # is set; these workers must stay CPU-pure (see __graft_entry__).
        scrub_accelerator_env(env)
        try:
            procs.append(subprocess.Popen(
                [sys.executable, "-c", _WORKER], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
        except OSError as e:
            pytest.skip(f"cannot spawn subprocess: {e}")
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"p{pid} rc={p.returncode}:\n{out[-3000:]}"
        assert f"MULTIHOST_OK p{pid}" in out, out[-2000:]
