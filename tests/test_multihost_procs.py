"""parallel/multihost across ≥2 REAL processes.

The single-process tests prove the API; a v5p pod runs N processes over one
global device set, and `jax.distributed` behaves differently there (device
visibility, process_index, cross-process array stitching). This spawns two
CPU processes — each playing one "host" that landed its own byte range —
initializes jax.distributed between them, stitches
``global_from_local_shards``, and asserts the assembled Array equals the
concatenated per-process landings (verified in every process via a psum
fingerprint, since no single process holds all shards addressably).

Skipped only when the runtime can't spawn subprocesses. In the default
selection since round 5: both scenarios finish in ~12s combined, and the
cross-process fabric is exactly what the suite must prove every run.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys

import pytest

from dragonfly2_tpu.pkg.hermetic import scrub_accelerator_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cpu_multiprocess_collectives() -> bool:
    """Old jaxlib CPU clients abort cross-process computations with
    "Multiprocess computations aren't implemented on the CPU backend";
    the capable client (gloo-backed cross-host collectives) ships with
    jax >= 0.5. Version-gate rather than probe: the probe IS the 2-process
    spawn these tests do."""
    import jax

    try:
        ver = tuple(int(x) for x in jax.__version__.split(".")[:2])
    except ValueError:
        return True   # unparseable dev version: assume capable
    return ver >= (0, 5)


_needs_multiproc_cpu = pytest.mark.skipif(
    not _cpu_multiprocess_collectives(),
    reason="jaxlib CPU backend lacks multiprocess collectives (< 0.5)")

_WORKER = r"""
import os, sys
sys.path.insert(0, os.environ["DF_REPO"])

import numpy as np
import jax

from dragonfly2_tpu.parallel import multihost

pid = int(os.environ["DF_PROC_ID"])
nprocs = int(os.environ["DF_NUM_PROCS"])

multihost.initialize_distributed(
    coordinator_address=os.environ["DF_COORD"],
    num_processes=nprocs, process_id=pid)
assert jax.process_count() == nprocs, jax.process_count()
assert jax.process_index() == pid

from jax.sharding import Mesh, PartitionSpec as P

devices = np.array(jax.devices())          # global: both processes' devices
n = devices.size
mesh = Mesh(devices.reshape(n), ("d",))

# Each "host" landed its own contiguous byte range: rows are globally
# numbered so equality against the concatenation is checkable anywhere.
rows_per_proc = (n // nprocs) * 2          # 2 rows per local device
cols = 8
base = pid * rows_per_proc
local = (np.arange(rows_per_proc * cols, dtype=np.float32)
         .reshape(rows_per_proc, cols) + base * cols)

arr = multihost.global_from_local_shards(mesh, local, axis_name="d")
assert arr.shape == (rows_per_proc * nprocs, cols), arr.shape

# Global verification without materializing remote shards: the sum of the
# assembled Array (an XLA cross-process reduction) must equal the sum of
# the full concatenation, and a weighted sum pins each row to its slot.
total_rows = rows_per_proc * nprocs
want = (np.arange(total_rows * cols, dtype=np.float64)
        .reshape(total_rows, cols))
weights = np.linspace(1.0, 2.0, total_rows, dtype=np.float64)[:, None]

got_sum = float(jax.jit(lambda a: a.astype("float64").sum())(arr))
assert abs(got_sum - want.sum()) < 1e-6, (got_sum, want.sum())
got_w = float(jax.jit(
    lambda a: (a.astype("float64") * weights).sum())(arr))
assert abs(got_w - (want * weights).sum()) < 1e-3, (got_w,)

# Local shards really live on this process's devices with the right data.
for shard in arr.addressable_shards:
    lo = shard.index[0].start or 0
    np.testing.assert_array_equal(
        np.asarray(shard.data),
        want[lo:lo + shard.data.shape[0]].astype(np.float32))

print(f"MULTIHOST_OK p{pid}")
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@_needs_multiproc_cpu
def test_two_process_global_assembly(tmp_path):
    nprocs = 2
    coord = f"127.0.0.1:{_free_port()}"
    procs = []
    for pid in range(nprocs):
        env = dict(os.environ)
        env.update({
            "DF_REPO": REPO,
            "DF_COORD": coord,
            "DF_PROC_ID": str(pid),
            "DF_NUM_PROCS": str(nprocs),
            "JAX_PLATFORMS": "cpu",
            # 2 local devices per process → 4 global.
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        })
        # The sandbox sitecustomize dials an accelerator relay when this
        # is set; these workers must stay CPU-pure (see __graft_entry__).
        scrub_accelerator_env(env)
        try:
            procs.append(subprocess.Popen(
                [sys.executable, "-c", _WORKER], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
        except OSError as e:
            pytest.skip(f"cannot spawn subprocess: {e}")
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"p{pid} rc={p.returncode}:\n{out[-3000:]}"
        assert f"MULTIHOST_OK p{pid}" in out, out[-2000:]


_ORIGIN = r"""
import asyncio, os, sys
sys.path.insert(0, os.environ["DF_REPO"])
from aiohttp import web
from dragonfly2_tpu.pkg.piece import Range

CKPT = open(os.environ["DF_CKPT"], "rb").read()
stats = {"bytes": 0}

async def blob(request):
    rng = request.headers.get("Range")
    if rng:
        r = Range.parse_http(rng, len(CKPT))
        data = CKPT[r.start:r.start + r.length]   # count SERVED bytes
        stats["bytes"] += len(data)
        return web.Response(status=206, body=data,
            headers={"Content-Range":
                     f"bytes {r.start}-{r.start + len(data) - 1}/{len(CKPT)}",
                     "Accept-Ranges": "bytes"})
    stats["bytes"] += len(CKPT)
    return web.Response(body=CKPT, headers={"Accept-Ranges": "bytes"})

async def served(request):
    return web.json_response(stats)

_waiters = {"n": 0, "event": asyncio.Event()}

async def barrier(request):
    # Aligns the workers between their (skewed) fabric phases and their
    # first cross-process collective, whose deadline is much shorter
    # than the possible compile/download skew on a contended core.
    want = int(request.query.get("n", "2"))
    _waiters["n"] += 1
    if _waiters["n"] >= want:
        _waiters["event"].set()
    await _waiters["event"].wait()
    return web.Response(text="go")

async def main():
    app = web.Application()
    app.router.add_get("/ckpt.safetensors", blob)
    app.router.add_get("/stats", served)
    app.router.add_get("/barrier", barrier)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    print("PORT", site._server.sockets[0].getsockname()[1], flush=True)
    await asyncio.sleep(600)

asyncio.run(main())
"""

_SHARD_WORKER = r"""
import asyncio, os, sys
sys.path.insert(0, os.environ["DF_REPO"])

import numpy as np
import jax

from dragonfly2_tpu.parallel import multihost

pid = int(os.environ["DF_PROC_ID"])
nprocs = int(os.environ["DF_NUM_PROCS"])

multihost.initialize_distributed(
    coordinator_address=os.environ["DF_COORD"],
    num_processes=nprocs, process_id=pid)
assert jax.process_count() == nprocs

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dragonfly2_tpu.client import device as device_lib
from dragonfly2_tpu.daemon.config import DaemonConfig
from dragonfly2_tpu.daemon.daemon import Daemon

devices = np.array(jax.devices())
mesh = Mesh(devices.reshape(devices.size), ("d",))
sharding = NamedSharding(mesh, P("d", None))


async def pull_my_shards():
    cfg = DaemonConfig()
    cfg.work_home = os.environ["DF_HOME"]
    cfg.__post_init__()
    cfg.host.hostname = f"shardhost{pid}"
    cfg.host.ip = "127.0.0.1"
    cfg.scheduler.addrs = [os.environ["DF_SCHED"]]
    cfg.gc_interval = 3600
    cfg.tpu_sink.enabled = True
    d = Daemon(cfg)
    await d.start()
    try:
        # download_global: THIS process pulls only the byte ranges its
        # local devices hold under the global sharding, and the result
        # is already a pod-global jax.Array.
        got = await device_lib.download_global(
            d, os.environ["DF_URL"], {"w": sharding})
        return got["w"]
    finally:
        await d.stop()


arr = asyncio.run(pull_my_shards())

# Align with the other worker before the first cross-process collective:
# fabric-phase skew (downloads + XLA compiles on a contended core) can
# exceed the collective's deadline.
import urllib.request

base = os.environ["DF_URL"].rsplit("/", 1)[0]
urllib.request.urlopen(f"{base}/barrier?n={nprocs}", timeout=180).read()

rows = arr.shape[0] // nprocs
cols = arr.shape[1]
assert arr.sharding.is_equivalent_to(sharding, len(arr.shape))

# The logical weight is arange over the full matrix: a global reduction
# (cross-process XLA collective) checks every shard landed in its slot.
total = rows * nprocs * cols
want_sum = float(np.arange(total, dtype=np.float64).sum())
got_sum = float(jax.jit(lambda a: a.sum())(arr))
# Relative tolerances: x64 is disabled in the workers, and a shard in
# the wrong slot shifts the weighted sum by whole percents.
assert abs(got_sum - want_sum) < 1e-4 * want_sum, (got_sum, want_sum)
w = np.linspace(1.0, 2.0, rows * nprocs, dtype=np.float32)[:, None]
want_w = float((np.arange(total, dtype=np.float64)
                .reshape(rows * nprocs, cols) * w).sum())
got_w = float(jax.jit(lambda a: (a * w).sum())(arr))
assert abs(got_w - want_w) < 1e-4 * want_w, (got_w, want_w)

print(f"SHARDED_POD_OK p{pid}")
"""


@_needs_multiproc_cpu
def test_sharded_pod_pull_end_to_end(tmp_path):
    """The full north-star chain across REAL process boundaries: a
    safetensors checkpoint at an origin; a scheduler process; two
    jax.distributed worker processes that each embed a daemon, pull ONLY
    their own shard via download_sharded (ranged device tasks through
    the fabric), and assemble the shards into one pod-global jax.Array
    verified by cross-process collectives. Origin must serve each byte
    ~once across BOTH workers (the shared header spans dedup via P2P)."""
    import json as _json
    import struct
    import urllib.request

    import numpy as np

    rows, cols = 128, 32     # one logical weight; 4 global devices shard rows
    full = np.arange(rows * cols, dtype=np.float32).reshape(rows, cols)
    raw = full.tobytes()
    header = {"w": {"dtype": "F32", "shape": [rows, cols],
                    "data_offsets": [0, len(raw)]}}
    hj = _json.dumps(header).encode()
    ckpt = struct.pack("<Q", len(hj)) + hj + raw
    ckpt_path = str(tmp_path / "ckpt.safetensors")
    with open(ckpt_path, "wb") as f:
        f.write(ckpt)

    base_env = scrub_accelerator_env(dict(os.environ))
    base_env["DF_REPO"] = REPO
    base_env.pop("XLA_FLAGS", None)
    base_env["JAX_PLATFORMS"] = "cpu"

    sched_port = _free_port()
    try:
        origin = subprocess.Popen(
            [sys.executable, "-c", _ORIGIN],
            env={**base_env, "DF_CKPT": ckpt_path},
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        sched = subprocess.Popen(
            [sys.executable, "-m", "dragonfly2_tpu.cli.main", "scheduler",
             "--host", "127.0.0.1", "--port", str(sched_port)],
            env={**base_env, "PYTHONPATH": REPO},
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    except OSError as e:
        pytest.skip(f"cannot spawn subprocess: {e}")
    workers = []
    try:
        # stderr merges into stdout: skim warnings until the PORT line.
        oport = None
        for _ in range(50):
            line = origin.stdout.readline().strip()
            if line.startswith("PORT "):
                oport = int(line.split()[1])
                break
        assert oport is not None, "origin never printed its port"
        url = f"http://127.0.0.1:{oport}/ckpt.safetensors"

        coord = f"127.0.0.1:{_free_port()}"
        for pid in range(2):
            env = dict(base_env)
            env.update({
                "DF_COORD": coord, "DF_PROC_ID": str(pid),
                "DF_NUM_PROCS": "2", "DF_SCHED": f"127.0.0.1:{sched_port}",
                "DF_URL": url, "DF_HOME": str(tmp_path / f"w{pid}"),
                "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
            })
            workers.append(subprocess.Popen(
                [sys.executable, "-c", _SHARD_WORKER], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
        outs = []
        for p in workers:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
        failures = [
            f"w{pid} rc={p.returncode}:\n{out[-9000:]}"
            for pid, (p, out) in enumerate(zip(workers, outs))
            if p.returncode != 0 or f"SHARDED_POD_OK p{pid}" not in out]
        assert not failures, "\n\n=====\n".join(failures)

        # Origin economy across the pod: each worker's shard range once
        # + the header-guess task (whole tiny file), which can cold-race
        # once per worker when both register simultaneously with no seed
        # to dedup against — ≈3 copies ceiling for a tiny file. Real
        # checkpoints amortize the guess to ~1 shard-set + 256K/worker
        # worst case; preheated (seeded) pods dedup it to once.
        with urllib.request.urlopen(f"http://127.0.0.1:{oport}/stats",
                                    timeout=10) as resp:
            served = _json.loads(resp.read())["bytes"]
        assert served <= int(len(ckpt) * 3.3), (served, len(ckpt))
    finally:
        for p in workers:
            if p.poll() is None:
                p.kill()
        origin.kill()
        sched.kill()
