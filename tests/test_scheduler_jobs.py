"""Scheduler job worker: manager-queued preheat / sync-peers / get / delete
executed against a live scheduler + seed daemon.

Reference model: scheduler/job/job.go consumed machinery queues and fanned
preheats to seed peers (preheat :161, :252 allSeedPeers) — here the full
loop runs hermetically: manager REST/queue → drpc long-poll → JobWorker →
Peer.TriggerDownloadTask on the seed daemon → origin, with group results
aggregated back into the manager's jobs table.
"""

from __future__ import annotations

import asyncio

from dragonfly2_tpu.manager.config import ManagerConfig
from dragonfly2_tpu.manager.server import ManagerServer
from dragonfly2_tpu.pkg import idgen
from dragonfly2_tpu.scheduler.config import SchedulerConfig
from dragonfly2_tpu.scheduler.server import SchedulerServer

from tests.test_p2p_e2e import start_daemon, start_origin


async def _wait(predicate, timeout: float = 15.0, interval: float = 0.05):
    deadline = asyncio.get_running_loop().time() + timeout
    while asyncio.get_running_loop().time() < deadline:
        if predicate():
            return True
        await asyncio.sleep(interval)
    return False


async def _cluster(tmp_path):
    """manager + scheduler(joined) + seed daemon, all ephemeral ports."""
    manager = ManagerServer(ManagerConfig())
    await manager.start()
    cfg = SchedulerConfig()
    cfg.server.port = 0
    cfg.scheduling.retry_interval = 0.05
    cfg.gc.interval = 3600
    cfg.manager_addr = f"127.0.0.1:{manager.grpc_port()}"
    sched = SchedulerServer(cfg)
    await sched.start()
    assert sched.job_worker is not None
    seed = await start_daemon(tmp_path, "seed", sched.port(), seed=True)
    await _wait(lambda: any(h.is_seed() for h in sched.service.hosts.all()))
    return manager, sched, seed


def test_preheat_job_end_to_end(run_async, tmp_path):
    async def run():
        runner, port, stats = await start_origin()
        manager, sched, seed = await _cluster(tmp_path)
        try:
            url = f"http://127.0.0.1:{port}/blob"
            cluster_id = sched.announcer.registered["scheduler_cluster_id"]
            job = manager.service.jobs.enqueue_job(
                "preheat", {"url": url, "scope": "all_seed_peers",
                            "timeout": 20.0}, [cluster_id])
            assert await _wait(lambda: manager.db.get("jobs", job["id"])
                               ["state"] in ("SUCCESS", "FAILURE"), 30.0)
            row = manager.db.get("jobs", job["id"])
            assert row["state"] == "SUCCESS", row
            results = row["result"]["group_results"]
            assert results and results[0]["preheat"][0]["triggered"] == 1
            # Seed actually holds the bytes.
            task_id = idgen.task_id_v1(url)
            store = seed.task_manager.storage.try_get(task_id)
            assert store is not None and store.metadata.done
            assert stats["blob_streams"] >= 1
        finally:
            await seed.stop()
            await sched.stop()
            await manager.stop()
            await runner.cleanup()

    run_async(run())


def test_get_and_delete_task_jobs(run_async, tmp_path):
    async def run():
        runner, port, stats = await start_origin()
        manager, sched, seed = await _cluster(tmp_path)
        try:
            url = f"http://127.0.0.1:{port}/blob"
            cluster_id = sched.announcer.registered["scheduler_cluster_id"]
            task_id = idgen.task_id_v1(url)
            # Preheat first so the task exists on the seed.
            manager.service.jobs.enqueue_job(
                "preheat", {"url": url, "timeout": 20.0}, [cluster_id])
            assert await _wait(
                lambda: (s := seed.task_manager.storage.try_get(task_id))
                is not None and s.metadata.done, 30.0)

            job = manager.service.jobs.enqueue_job(
                "get_task", {"task_id": task_id}, [cluster_id])
            assert await _wait(lambda: manager.db.get("jobs", job["id"])
                               ["state"] == "SUCCESS", 15.0)
            peers = manager.db.get("jobs", job["id"])["result"][
                "group_results"][0]["peers"]
            assert any(p["hostname"] == "seed" for p in peers)

            job = manager.service.jobs.enqueue_job(
                "delete_task", {"task_id": task_id}, [cluster_id])
            assert await _wait(lambda: manager.db.get("jobs", job["id"])
                               ["state"] == "SUCCESS", 15.0)
            assert seed.task_manager.storage.try_get(task_id) is None
        finally:
            await seed.stop()
            await sched.stop()
            await manager.stop()
            await runner.cleanup()

    run_async(run())


def test_sync_peers_job_populates_manager_table(run_async, tmp_path):
    async def run():
        runner, port, _ = await start_origin()
        manager, sched, seed = await _cluster(tmp_path)
        try:
            cluster_id = sched.announcer.registered["scheduler_cluster_id"]
            job = manager.service.jobs.enqueue_job("sync_peers", {}, [cluster_id])
            assert await _wait(lambda: manager.db.get("jobs", job["id"])
                               ["state"] == "SUCCESS", 15.0)
            synced = manager.db.get("jobs", job["id"])["result"][
                "group_results"][0]["synced"]
            assert synced >= 1
            assert manager.db.find("peers", hostname="seed") is not None
        finally:
            await seed.stop()
            await sched.stop()
            await manager.stop()
            await runner.cleanup()

    run_async(run())


def test_sharded_preheat_ranges(run_async, tmp_path):
    """Sharded preheat: args.ranges warms each byte span as its own
    ranged task — the seed ends up holding exactly the slices, so a
    stage group warms only its tensors' spans (the job-level face of
    download_sharded)."""

    async def run():
        import tests.test_p2p_e2e as e2e

        runner, port, stats = await start_origin()
        manager, sched, seed = await _cluster(tmp_path)
        try:
            url = f"http://127.0.0.1:{port}/blob"
            cluster_id = sched.announcer.registered["scheduler_cluster_id"]
            spans = ["0-65535", "1048576-2097151"]
            job = manager.service.jobs.enqueue_job(
                "preheat", {"url": url, "ranges": spans,
                            "scope": "all_seed_peers", "timeout": 20.0},
                [cluster_id])
            assert await _wait(lambda: manager.db.get("jobs", job["id"])
                               ["state"] in ("SUCCESS", "FAILURE"), 30.0)
            row = manager.db.get("jobs", job["id"])
            assert row["state"] == "SUCCESS", row
            results = row["result"]["group_results"][0]["preheat"]
            assert {r["range"] for r in results} == {
                "bytes=0-65535", "bytes=1048576-2097151"}

            # The seed holds each RANGED task's bytes (slice-exact), and
            # served well under the whole file from origin.
            for span in spans:
                task_id = idgen.task_id_v1(
                    url, range_header=f"bytes={span}")
                store = seed.task_manager.storage.try_get(task_id)
                assert store is not None and store.metadata.done, span
                a, b = (int(x) for x in span.split("-"))
                assert store.metadata.content_length == b - a + 1
            assert stats["blob_bytes"] < len(e2e.CONTENT), stats
        finally:
            await seed.stop()
            await sched.stop()
            await manager.stop()
            await runner.cleanup()

    run_async(run())


def test_sharded_preheat_rejects_bad_ranges(run_async, tmp_path):
    """Malformed spans must fail the job immediately with the span named
    — not burn the wait timeout against tasks that can never exist."""

    async def run():
        runner, port, stats = await start_origin()
        manager, sched, seed = await _cluster(tmp_path)
        try:
            url = f"http://127.0.0.1:{port}/blob"
            cluster_id = sched.announcer.registered["scheduler_cluster_id"]
            for bad in ({"ranges": "0-65535"},          # str, not list
                        {"ranges": ["10-5"]},           # inverted
                        {"ranges": ["-1024"]},          # suffix span
                        {"range": "nonsense"}):
                job = manager.service.jobs.enqueue_job(
                    "preheat", {"url": url, "timeout": 20.0, **bad},
                    [cluster_id])
                assert await _wait(
                    lambda: manager.db.get("jobs", job["id"])["state"]
                    in ("SUCCESS", "FAILURE"), 10.0)
                row = manager.db.get("jobs", job["id"])
                assert row["state"] == "FAILURE", bad
                err = row["result"]["group_results"][0]["error"]
                assert "range" in err, (bad, err)
            assert stats["blob_streams"] == 0  # nothing ever triggered
        finally:
            await seed.stop()
            await sched.stop()
            await manager.stop()
            await runner.cleanup()

    run_async(run())
