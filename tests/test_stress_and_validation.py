"""Daemon-surface load test + manager REST input validation breadth.

VERDICT r2: no stress tool analog of test/tools/stress/main.go existed,
and the manager's generic CRUD trusted body shape. benchmarks/stress.py
is the load generator; these tests run it at unit scale against a live
upload server and pin the REST API's behavior on malformed input (400s,
never 500s or crashes).
"""

from __future__ import annotations

import asyncio
import random

import aiohttp
import pytest

from benchmarks.stress import run_stress
from dragonfly2_tpu.manager.rest import RestServer
from dragonfly2_tpu.manager.service import ManagerService


def test_stress_upload_surface(run_async, tmp_path):
    """Concurrent piece GETs against a live upload server: every request
    succeeds and the sendfile path sustains concurrency."""
    from dragonfly2_tpu.daemon.upload import UploadManager
    from dragonfly2_tpu.storage import StorageManager
    from dragonfly2_tpu.storage.manager import StorageOption
    from dragonfly2_tpu.storage.local_store import TaskStoreMetadata

    async def body():
        storage = StorageManager(StorageOption(data_dir=str(tmp_path / "d")))
        piece = 256 * 1024
        content = random.Random(5).randbytes(piece * 4)
        store = storage.register_task(TaskStoreMetadata(
            task_id="stress-task", content_length=len(content),
            piece_size=piece, total_piece_count=4))
        for n in range(4):
            store.write_piece(n, content[n * piece:(n + 1) * piece])
        store.mark_done()

        upload = UploadManager(storage)
        port = await upload.serve("127.0.0.1", 0)
        try:
            result = await run_stress(
                f"http://127.0.0.1:{port}/download/str/stress-task"
                f"?peerId=x&pieceNum=2",
                concurrency=8, duration=2.0)
            assert result["ok"] > 0
            assert not result["errors"], result
            assert result["rps"] > 10, result
        finally:
            await upload.close()
            storage.close()

    run_async(body(), timeout=60)


def test_manager_rest_malformed_bodies(run_async):
    """Malformed input at every class — invalid JSON, wrong types,
    missing fields, bad resource ids — returns 4xx, never 500."""

    async def body():
        svc = ManagerService()
        rest = RestServer(svc)
        port = await rest.serve("127.0.0.1", 0)
        base = f"http://127.0.0.1:{port}/api/v1"
        try:
            async with aiohttp.ClientSession() as http:
                async with http.post(f"{base}/users/signin",
                                     json={"name": "root",
                                           "password": "dragonfly"}) as r:
                    token = (await r.json())["token"]
                h = {"Authorization": f"Bearer {token}"}

                cases = [
                    # invalid JSON body
                    ("POST", "/users/signin", b"{not json", {}),
                    # missing required fields
                    ("POST", "/users/signin", b"{}", {}),
                    ("POST", "/jobs", b"{}", h),
                    # wrong types
                    ("POST", "/users/signin",
                     b'{"name": 42, "password": []}', {}),
                    # bad id in path
                    ("GET", "/users/not-a-number", b"", h),
                    ("PATCH", "/scheduler-clusters/999999",
                     b'{"name": "x"}', h),
                    # role grant for missing user id form
                    ("PUT", "/users/abc/roles/root", b"", h),
                ]
                for method, path, payload, headers in cases:
                    async with http.request(
                            method, base + path, data=payload,
                            headers={**headers,
                                     "Content-Type": "application/json"}) as r:
                        assert 400 <= r.status < 500, (
                            method, path, r.status, await r.text())
        finally:
            await rest.close()

    run_async(body(), timeout=60)


def test_manager_rest_drpc_schema_rejects_bad_updates(run_async):
    """The drpc manager surface rejects type-violating registration
    bodies at the wire boundary (proto/wire.py)."""
    from dragonfly2_tpu.manager.rpcserver import ManagerRpcServer
    from dragonfly2_tpu.pkg.errors import Code, DfError
    from dragonfly2_tpu.pkg.types import NetAddr
    from dragonfly2_tpu.rpc import Client, Server

    async def body():
        svc = ManagerService()
        server = Server("manager-test")
        ManagerRpcServer(svc).register(server)
        await server.serve(NetAddr.tcp("127.0.0.1", 0))
        cli = Client(NetAddr.tcp("127.0.0.1", server.port()))
        try:
            with pytest.raises(DfError) as ei:
                await cli.call("Manager.UpdateScheduler",
                               {"hostname": "h"})  # ip missing
            assert ei.value.code == Code.BadRequest
            with pytest.raises(DfError) as ei:
                await cli.call("Manager.PollJob", {"queue": 7})
            assert ei.value.code == Code.BadRequest
        finally:
            await cli.close()
            await server.close()

    run_async(body(), timeout=60)
