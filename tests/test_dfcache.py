"""dfcache: import on one daemon, export on another over P2P only.

Reference: client/dfcache/dfcache.go Import/Export/Stat/Delete + scheduler
AnnounceTask (service_v1.go:331) making the importer a parent candidate.
"""

from __future__ import annotations

import hashlib
import os

import pytest

from dragonfly2_tpu.client import dfcache
from dragonfly2_tpu.pkg.errors import DfError

from tests.test_p2p_e2e import start_daemon, start_scheduler


def test_import_export_across_daemons(run_async, tmp_path):
    async def run():
        sched = await start_scheduler()
        d_a = await start_daemon(tmp_path, "peer-a", sched.port())
        d_b = await start_daemon(tmp_path, "peer-b", sched.port())
        try:
            payload = os.urandom(2 * 1024 * 1024)
            src = tmp_path / "model.bin"
            src.write_bytes(payload)

            cfg_a = dfcache.DfcacheConfig(
                daemon_sock=d_a.config.unix_sock, cache_id="ckpt-v1", tag="t")
            result = await dfcache.import_file(cfg_a, str(src))
            assert result["content_length"] == len(payload)
            assert result["pieces"] >= 1

            # Importer stats it locally.
            stat = await dfcache.stat(cfg_a)
            assert stat["done"] and stat["content_length"] == len(payload)

            # The scheduler now knows this task (AnnounceTask).
            task = sched.service.tasks.load(dfcache.task_id_of(cfg_a))
            assert task is not None and task.state == "succeeded"

            # Export from the OTHER daemon: must come via P2P (no origin
            # exists for dfcache:// URLs, so P2P is the only route).
            cfg_b = dfcache.DfcacheConfig(
                daemon_sock=d_b.config.unix_sock, cache_id="ckpt-v1", tag="t")
            out = tmp_path / "exported.bin"
            final = await dfcache.export_file(cfg_b, str(out))
            assert final["state"] == "done"
            assert hashlib.sha256(out.read_bytes()).hexdigest() == \
                hashlib.sha256(payload).hexdigest()

            # Delete on the importer.
            await dfcache.delete(cfg_a)
            with pytest.raises(DfError):
                await dfcache.stat(cfg_a)
        finally:
            await d_a.stop()
            await d_b.stop()
            await sched.stop()

    run_async(run())


def test_export_missing_entry_fails_without_origin(run_async, tmp_path):
    async def run():
        sched = await start_scheduler()
        d = await start_daemon(tmp_path, "peer-x", sched.port())
        try:
            cfg = dfcache.DfcacheConfig(
                daemon_sock=d.config.unix_sock, cache_id="never-imported")
            with pytest.raises(DfError):
                await dfcache.export_file(cfg, str(tmp_path / "out.bin"))
        finally:
            await d.stop()
            await sched.stop()

    run_async(run())
