"""Scheduler unit tests: resource FSMs, evaluator scoring, scheduling filters.

Modeled on reference scheduler/scheduling/scheduling_test.go and
evaluator_base_test.go (build fake hosts/peers, assert filter + sort
behavior).
"""

import pytest

from dragonfly2_tpu.pkg.types import HostType
from dragonfly2_tpu.scheduler.config import SchedulingConfig
from dragonfly2_tpu.scheduler.resource import (
    Host,
    Peer,
    PeerState,
    Task,
    TaskState,
)
from dragonfly2_tpu.scheduler.scheduling import Evaluator, Scheduling


def make_host(hid, *, host_type=HostType.NORMAL, idc="", location="", tpu_slice="",
              upload_port=9000):
    return Host(hid, ip="10.0.0.1", port=8000, upload_port=upload_port,
                host_type=host_type, idc=idc, location=location, tpu_slice=tpu_slice)


def make_peer(pid, task, host, *, state=None, pieces=0):
    p = Peer(pid, task, host)
    task.add_peer(p)
    host.peer_ids.add(pid)
    if state == PeerState.RUNNING:
        p.fsm.event("register_normal")
        p.fsm.event("download")
    elif state == PeerState.SUCCEEDED:
        p.fsm.event("register_normal")
        p.fsm.event("download")
        p.fsm.event("download_succeeded")
    elif state == PeerState.BACK_TO_SOURCE:
        p.fsm.event("register_normal")
        p.fsm.event("download_back_to_source")
    for i in range(pieces):
        p.add_finished_piece(i, cost_ms=50)
    return p


class TestFSMs:
    def test_task_lifecycle(self):
        t = Task("t1", "http://x")
        assert t.state == TaskState.PENDING
        t.fsm.event("download")
        assert t.state == TaskState.RUNNING
        t.fsm.event("download_succeeded")
        assert t.state == TaskState.SUCCEEDED
        t.fsm.event("download")  # re-download allowed
        assert t.state == TaskState.RUNNING

    def test_peer_lifecycle(self):
        t = Task("t1")
        h = make_host("h1")
        p = Peer("p1", t, h)
        p.fsm.event("register_normal")
        p.fsm.event("download")
        p.fsm.event("download_succeeded")
        assert p.is_done()

    def test_peer_back_to_source_path(self):
        t = Task("t1")
        p = Peer("p1", t, make_host("h1"))
        p.fsm.event("register_normal")
        p.fsm.event("download_back_to_source")
        assert p.state == PeerState.BACK_TO_SOURCE
        p.fsm.event("download_succeeded")
        assert p.state == PeerState.SUCCEEDED


class TestEvaluator:
    def test_more_pieces_scores_higher(self):
        t = Task("t1")
        t.total_piece_count = 10
        child = make_peer("c", t, make_host("hc"))
        rich = make_peer("rich", t, make_host("h1"), state=PeerState.RUNNING, pieces=9)
        poor = make_peer("poor", t, make_host("h2"), state=PeerState.RUNNING, pieces=1)
        ev = Evaluator()
        ranked = ev.evaluate_parents([poor, rich], child, 10)
        assert ranked[0].id == "rich"

    def test_seed_outranks_normal(self):
        t = Task("t1")
        t.total_piece_count = 10
        child = make_peer("c", t, make_host("hc"))
        seed = make_peer("seed", t, make_host("hs", host_type=HostType.SUPER_SEED),
                         state=PeerState.SUCCEEDED, pieces=10)
        normal = make_peer("n", t, make_host("hn"), state=PeerState.SUCCEEDED, pieces=10)
        ev = Evaluator()
        ranked = ev.evaluate_parents([normal, seed], child, 10)
        assert ranked[0].id == "seed"

    def test_same_slice_beats_cross_slice(self):
        """TPU topology: an ICI-local parent must beat a remote seed-grade
        parent with the same piece count."""
        t = Task("t1")
        t.total_piece_count = 10
        child = make_peer("c", t, make_host("hc", tpu_slice="slice-a", idc="pod-1"))
        local = make_peer("local", t,
                          make_host("h1", tpu_slice="slice-a", idc="pod-1"),
                          state=PeerState.SUCCEEDED, pieces=10)
        remote = make_peer("remote", t,
                           make_host("h2", tpu_slice="slice-z", idc="pod-9"),
                           state=PeerState.SUCCEEDED, pieces=10)
        ev = Evaluator()
        ranked = ev.evaluate_parents([remote, local], child, 10)
        assert ranked[0].id == "local"

    def test_location_affinity_prefix(self):
        ev = Evaluator()
        a = make_host("a", location="us|pod1|slice1|host1")
        b = make_host("b", location="us|pod1|slice2|host9")
        c = make_host("c", location="eu|podx")
        assert ev._location_score(a, b) == pytest.approx(2 / 5)
        assert ev._location_score(a, c) == 0.0

    def test_bad_node_20x_mean(self):
        t = Task("t1")
        p = make_peer("p", t, make_host("h"))
        for _ in range(5):
            p.piece_costs.append(10)
        p.piece_costs.append(500)  # 50x mean
        assert Evaluator.is_bad_node(p)

    def test_bad_node_3_sigma(self):
        t = Task("t1")
        p = make_peer("p", t, make_host("h"))
        for _ in range(35):
            p.piece_costs.append(100)
        p.piece_costs.append(101)  # sigma 0 → any increase trips
        assert Evaluator.is_bad_node(p)
        p2 = make_peer("p2", t, make_host("h2"))
        for i in range(35):
            p2.piece_costs.append(100 + (i % 5))
        p2.piece_costs.append(103)  # within band
        assert not Evaluator.is_bad_node(p2)


class TestSchedulingFilters:
    def _setup(self):
        cfg = SchedulingConfig(retry_interval=0.01)
        s = Scheduling(cfg)
        t = Task("t1", "http://x")
        t.total_piece_count = 10
        child = make_peer("child", t, make_host("hc"))
        return s, t, child

    def test_filters_self_and_same_host(self):
        s, t, child = self._setup()
        same_host = make_peer("same", t, child.host, state=PeerState.RUNNING, pieces=5)
        assert s.find_candidate_parents(child) == []

    def test_filters_blocklist_and_states(self):
        s, t, child = self._setup()
        good = make_peer("good", t, make_host("h1"), state=PeerState.RUNNING, pieces=5)
        pending = make_peer("pend", t, make_host("h2"))  # still pending
        parents = s.find_candidate_parents(child)
        assert [p.id for p in parents] == ["good"]
        assert s.find_candidate_parents(child, {"good"}) == []

    def test_filters_no_free_upload(self):
        s, t, child = self._setup()
        h = make_host("h1")
        h.concurrent_upload_count = h.concurrent_upload_limit
        make_peer("busy", t, h, state=PeerState.RUNNING, pieces=5)
        assert s.find_candidate_parents(child) == []

    def test_candidate_limit(self):
        s, t, child = self._setup()
        for i in range(10):
            make_peer(f"p{i}", t, make_host(f"h{i}"), state=PeerState.SUCCEEDED, pieces=10)
        parents = s.find_candidate_parents(child)
        assert len(parents) == s.config.candidate_parent_limit

    def test_reattach_edges(self):
        s, t, child = self._setup()
        p1 = make_peer("p1", t, make_host("h1"), state=PeerState.SUCCEEDED, pieces=10)
        p2 = make_peer("p2", t, make_host("h2"), state=PeerState.SUCCEEDED, pieces=10)
        s.reattach_peer(child, [p1])
        assert t.peer_out_degree("p1") == 1
        s.reattach_peer(child, [p2])
        assert t.peer_out_degree("p1") == 0
        assert t.peer_out_degree("p2") == 1

    def test_schedule_need_back_source_when_empty(self, run_async):
        s, t, child = self._setup()
        child.fsm.event("register_normal")

        async def body():
            result = await s.schedule_candidate_parents(child)
            from dragonfly2_tpu.scheduler.scheduling.scheduling import ScheduleResult

            assert result.kind == ScheduleResult.NEED_BACK_SOURCE

        run_async(body())


class TestUploadAccounting:
    def test_edges_hold_and_release_slots(self):
        from dragonfly2_tpu.scheduler.scheduling import Scheduling

        s = Scheduling(SchedulingConfig(retry_interval=0.01))
        t = Task("t1")
        t.total_piece_count = 10
        parent = make_peer("p", t, make_host("hp"), state=PeerState.SUCCEEDED, pieces=10)
        c1 = make_peer("c1", t, make_host("h1"))
        c2 = make_peer("c2", t, make_host("h2"))
        t.add_peer_edge("p", "c1")
        t.add_peer_edge("p", "c2")
        assert parent.host.concurrent_upload_count == 2
        t.delete_peer_in_edges("c1")
        assert parent.host.concurrent_upload_count == 1
        t.delete_peer("c2")
        assert parent.host.concurrent_upload_count == 0

    def test_full_parent_filtered(self):
        from dragonfly2_tpu.scheduler.scheduling import Scheduling

        s = Scheduling(SchedulingConfig(retry_interval=0.01))
        t = Task("t1")
        t.total_piece_count = 10
        h = make_host("hp")
        h.concurrent_upload_limit = 1
        parent = make_peer("p", t, h, state=PeerState.SUCCEEDED, pieces=10)
        c1 = make_peer("c1", t, make_host("h1"))
        t.add_peer_edge("p", "c1")  # slot taken
        c2 = make_peer("c2", t, make_host("h2"))
        assert s.find_candidate_parents(c2) == []


class TestSlotReleaseOnFinish:
    def test_download_finished_releases_parent_slots(self):
        """A finished child must hand back its parents' upload slots
        (regression: slots leaked until peer GC, starving the task)."""
        from dragonfly2_tpu.scheduler.service import SchedulerService

        svc = SchedulerService()
        task = Task("t-slots", "http://x")
        parent_host = make_host("hp")
        child_host = make_host("hc")
        parent = make_peer("pp", task, parent_host, state=PeerState.RUNNING, pieces=4)
        child = make_peer("pc", task, child_host, state=PeerState.RUNNING)
        task.add_peer_edge(parent.id, child.id)
        assert parent_host.concurrent_upload_count == 1

        svc._handle_download_finished(
            {"content_length": 1024, "piece_size": 256, "total_piece_count": 4},
            task, child)
        assert child.state == PeerState.SUCCEEDED
        assert parent_host.concurrent_upload_count == 0


class TestDirectPieceVerification:
    """The tiny inline-content cache must be digest-guarded: a corrupt or
    malicious finisher must not poison the content for later registrants
    (scheduler/service.py _verify_direct_piece)."""

    def _task(self, content: bytes, digest: str = "") -> Task:
        import hashlib

        from dragonfly2_tpu.pkg.piece import PieceInfo

        t = Task("t-tiny", "http://x", digest=digest)
        t.content_length = len(content)
        t.piece_size = 4 * 1024 * 1024
        t.total_piece_count = 1
        t.store_piece(PieceInfo(
            0, 0, len(content),
            digest="md5:" + hashlib.md5(content).hexdigest()))
        return t

    def test_accepts_matching_content(self):
        from dragonfly2_tpu.scheduler.service import SchedulerService

        content = b"tiny" * 10
        task = self._task(content)
        assert SchedulerService._verify_direct_piece(task, content)

    def test_rejects_on_piece_digest_mismatch(self):
        from dragonfly2_tpu.scheduler.service import SchedulerService

        content = b"tiny" * 10
        task = self._task(content)
        assert not SchedulerService._verify_direct_piece(task, b"x" * 40)

    def test_rejects_on_task_digest_mismatch(self):
        import hashlib

        from dragonfly2_tpu.scheduler.service import SchedulerService

        content = b"tiny" * 10
        task = Task("t-tiny2", "http://x",
                    digest="sha256:" + hashlib.sha256(b"other").hexdigest())
        task.content_length = len(content)
        assert not SchedulerService._verify_direct_piece(task, content)

    def test_accepts_when_no_digest_on_record(self):
        from dragonfly2_tpu.scheduler.service import SchedulerService

        task = Task("t-tiny3", "http://x")
        task.content_length = 8
        assert SchedulerService._verify_direct_piece(task, b"whatever")


class TestPieceReportIdempotency:
    def test_duplicate_piece_finished_is_a_noop(self):
        """The client's report flush is at-least-once (a cancelled flush
        restores a batch whose send may already have hit the wire), so the
        scheduler must apply duplicates idempotently: no double upload_count
        on the parent, no duplicate cost samples skewing bad-node stats."""
        from dragonfly2_tpu.scheduler.service import SchedulerService

        svc = SchedulerService()
        task = Task("t-dup", "http://x")
        parent = make_peer("pp-dup", task, make_host("hp-dup"),
                           state=PeerState.RUNNING, pieces=4)
        child = make_peer("pc-dup", task, make_host("hc-dup"),
                          state=PeerState.RUNNING)
        svc.peers.load_or_store(parent)
        svc.peers.load_or_store(child)

        report = {"piece_num": 0, "range_start": 0, "range_size": 256,
                  "digest": "crc32c:abc", "download_cost_ms": 12,
                  "dst_peer_id": parent.id}
        svc._apply_piece_finished(dict(report), task, child)
        assert parent.host.upload_count == 1
        assert child.finished_pieces == {0}
        assert list(child.piece_costs) == [12]

        svc._apply_piece_finished(dict(report), task, child)  # re-delivery
        assert parent.host.upload_count == 1
        assert child.finished_pieces == {0}
        assert list(child.piece_costs) == [12]


class TestICILexicographicRanking:
    def test_serving_slice_mate_outranks_cross_slice_seed(self):
        """A 1-piece slice-mate still downloading must rank ahead of a
        piece-complete cross-slice super seed: intra-slice transfer rides
        ICI, cross-slice rides the DCN NIC — a partition, not a weight
        (scheduling.find_candidate_parents)."""
        s = Scheduling(SchedulingConfig(retry_interval=0.01))
        t = Task("t-ici", "http://x")
        t.total_piece_count = 10
        child = make_peer("child", t,
                          make_host("hc", tpu_slice="slice-a", idc="pod-1"))
        make_peer("seed", t,
                  make_host("hs", host_type=HostType.SUPER_SEED,
                            tpu_slice="slice-z", idc="pod-1"),
                  state=PeerState.SUCCEEDED, pieces=10)
        make_peer("mate", t,
                  make_host("hm", tpu_slice="slice-a", idc="pod-1"),
                  state=PeerState.RUNNING, pieces=1)
        parents = s.find_candidate_parents(child)
        assert [p.id for p in parents] == ["mate", "seed"]

    def test_sliceless_slice_falls_back_to_cross_ingress(self):
        """The slice's first arrival has no serving slice-mate: the
        cross-slice seed must still be handed out (the broadcast tree's
        one DCN ingress per slice)."""
        s = Scheduling(SchedulingConfig(retry_interval=0.01))
        t = Task("t-ici2", "http://x")
        t.total_piece_count = 10
        child = make_peer("child", t,
                          make_host("hc", tpu_slice="slice-a", idc="pod-1"))
        make_peer("seed", t,
                  make_host("hs", host_type=HostType.SUPER_SEED,
                            tpu_slice="slice-z", idc="pod-1"),
                  state=PeerState.SUCCEEDED, pieces=10)
        parents = s.find_candidate_parents(child)
        assert [p.id for p in parents] == ["seed"]

    def test_warming_slice_mate_is_a_candidate(self):
        """A RUNNING 0-piece slice-mate with its parent edges wired is a
        valid candidate (the intra-slice relay chain): its pieces arrive
        over ICI moments later. The same peer with NO parents wired stays
        excluded — it produces nothing and burns the starvation window."""
        s = Scheduling(SchedulingConfig(retry_interval=0.01))
        t = Task("t-warm", "http://x")
        t.total_piece_count = 10
        child = make_peer("child", t,
                          make_host("hc", tpu_slice="slice-a"))
        seed = make_peer("seed", t,
                         make_host("hs", host_type=HostType.SUPER_SEED,
                                   tpu_slice="slice-z"),
                         state=PeerState.SUCCEEDED, pieces=10)
        mate = make_peer("mate", t,
                         make_host("hm", tpu_slice="slice-a"),
                         state=PeerState.RUNNING, pieces=0)
        # Not yet wired: excluded.
        assert [p.id for p in s.find_candidate_parents(child)] == ["seed"]
        t.add_peer_edge(seed.id, mate.id)  # mate now actively downloading
        assert [p.id for p in s.find_candidate_parents(child)] == ["mate", "seed"]

    def test_handout_never_only_warming_mates(self):
        """When warming slice-mates fill the candidate limit, the tail
        slot must be swapped for a parent that serves NOW — a handout of
        only 0-piece relays leaves ttfp hostage to the chain."""
        s = Scheduling(SchedulingConfig(retry_interval=0.01))
        t = Task("t-warm2", "http://x")
        t.total_piece_count = 10
        child = make_peer("child", t,
                          make_host("hc", tpu_slice="slice-a"))
        seed = make_peer("seed", t,
                         make_host("hs", host_type=HostType.SUPER_SEED,
                                   tpu_slice="slice-z"),
                         state=PeerState.SUCCEEDED, pieces=10)
        limit = s.config.candidate_parent_limit
        for i in range(limit + 1):
            m = make_peer(f"mate{i}", t,
                          make_host(f"hm{i}", tpu_slice="slice-a"),
                          state=PeerState.RUNNING, pieces=0)
            t.add_peer_edge(seed.id, m.id)
        parents = s.find_candidate_parents(child)
        assert len(parents) == limit
        assert "seed" in [p.id for p in parents]
