"""Pod lens + SLO engine: clock alignment under seeded jitter, bounded
flight digests, cross-host timeline merge, burn-rate evaluation, the new
debug endpoints, and the chaos-seeded 4-host REAL-process pod e2e.

The acceptance battery (ISSUE 8): a real scheduler + 4 real daemons with
a seeded slow host (chaos piece-body stalls), one corrupt body, and an
injected clock skew must yield a merged /debug/pod/<task>/timeline that
names the seeded host slowest with stall/dcn dominant, matches each
host's own /debug/flight autopsy within ±5% of wall, and prints an
alignment error bound that covers the injected skew — while the seeded
degradation flips an SLO's burn rate over threshold at /debug/slo.
"""

from __future__ import annotations

import asyncio
import json
import random

import pytest

from dragonfly2_tpu.pkg import flight, podlens, slo
from dragonfly2_tpu.pkg.fleet import FleetTimeSeries


# --------------------------------------------------------------------- #
# Clock estimator: the CI guard for alignment regressions
# --------------------------------------------------------------------- #

class TestClockEstimator:
    def test_error_within_bound_under_jitter_and_asymmetry(self):
        """The tier-1 alignment guard: for seeded true offsets, RTT
        jitter and ASYMMETRIC up/down legs, the estimate's error must
        stay inside the estimator's own reported bound — the NTP
        midpoint guarantee |err| <= rtt/2 the merge's printed bound
        relies on. 100 hosts x 6 samples each."""
        rng = random.Random(1234)
        clock = [0.0]
        est = podlens.ClockEstimator(clock=lambda: clock[0])
        truths = {}
        for h in range(100):
            true_off = rng.uniform(-2.0, 2.0)
            truths[f"h{h}"] = true_off
            for _ in range(6):
                ts = rng.uniform(0, 1000)           # scheduler send time
                d_up = rng.uniform(0.0005, 0.040)   # asymmetric legs
                d_down = rng.uniform(0.0005, 0.040)
                t0 = ts + true_off                  # host clock at send
                echo = ts + d_up                    # scheduler at receipt
                t1 = ts + d_up + d_down + true_off  # host clock at reply
                assert est.add_sample(f"h{h}", t0, t1, echo)
        for host, true_off in truths.items():
            off, err, n = est.estimate(host)
            assert n >= 1
            assert abs(off - true_off) <= err, (host, off, true_off, err)
            assert err <= 0.040 / 2 + 0.005, err   # min-rtt selection won

    def test_estimate_error_grows_with_sample_age(self):
        """An old tight sample must not report a stale-tight bound: the
        aged bound grows by the drift allowance, and a fresher (looser)
        sample eventually wins the min-aged-bound selection."""
        clock = [0.0]
        est = podlens.ClockEstimator(clock=lambda: clock[0])
        est.add_sample("h", 100.0, 100.001, 99.5)     # rtt 1ms, off 0.5
        _, err0, _ = est.estimate("h")
        clock[0] = 3600.0
        _, err1, _ = est.estimate("h")
        assert err1 > err0
        assert err1 >= 3600.0 * podlens.DRIFT_PPM * 1e-6

    def test_rejects_malformed_samples_and_defaults_unaligned(self):
        est = podlens.ClockEstimator()
        assert not est.add_sample("h", 10.0, 9.0, 5.0)   # negative rtt
        assert not est.add_sample("h", 10.0, 11.0, 0.0)  # no echo
        off, err, n = est.estimate("h")
        assert (off, n) == (0.0, 0)
        assert err == podlens.UNALIGNED_ERR_S

    def test_host_cap_is_lru(self):
        est = podlens.ClockEstimator(max_hosts=4)
        for h in range(10):
            est.add_sample(f"h{h}", 1.0, 1.01, 0.5)
        assert est.hosts_tracked() == 4
        assert est.estimate("h9")[2] == 1
        assert est.estimate("h0")[2] == 0     # evicted


# --------------------------------------------------------------------- #
# Flight digest: compact, bounded, honest
# --------------------------------------------------------------------- #

class TestFlightDigest:
    def _flight(self, pieces: int) -> flight.TaskFlight:
        tf = flight.TaskFlight("digest-t")
        tf.record(flight.EV_REGISTER)
        tf.record(flight.EV_SCHEDULED, -1, 0.0, "normal_task")
        for n in range(pieces):
            tf.record(flight.EV_REQUEST, n, 0.0, "10.0.0.1:80")
            tf.record(flight.EV_FIRST_BYTE, n)
            tf.record(flight.EV_LANDED, n, 3.0, "cross")
        tf.finish("done")
        return tf

    def test_digest_holds_byte_cap_under_soak(self):
        """8192 pieces through the ring: the digest still serializes
        under DIGEST_MAX_BYTES and says so truthfully."""
        d = flight.digest(self._flight(8192))
        raw = json.dumps({k: v for k, v in d.items() if k != "bytes"},
                         separators=(",", ":"))
        assert d["bytes"] == len(raw)
        assert d["bytes"] <= flight.DIGEST_MAX_BYTES
        assert d["pieces_truncated"] or len(d["pieces"]) <= 64

    def test_digest_carries_segments_phases_and_clock(self):
        tf = self._flight(8)
        d = flight.digest(tf, clock_samples=[(10.0, 10.01, 9.7)])
        assert d["state"] == "done"
        assert set(d["phases"]) == set(flight.PHASES)
        assert d["segments"], "phase segments must ship"
        assert all(len(s) == 3 for s in d["segments"])
        assert d["clock"] == [[10.0, 10.01, 9.7]]
        rows = flight.digest_piece_rows(d)
        assert rows[0]["piece"] == 0 and rows[0]["ok"] == 1
        # The digest's phase totals are the analyzer's — one source.
        rep = flight.analyze(tf)
        assert d["phases"] == rep["phases"]

    def test_tiny_cap_still_yields_valid_digest(self):
        d = flight.digest(self._flight(512), max_bytes=2048)
        assert d["bytes"] <= 2048
        assert d["phases"] and d["wall_s"] >= 0

    def test_recorder_wall_offset_skews_start_wall(self):
        rec = flight.FlightRecorder(wall_offset=1.5)
        tf = rec.task("skewed")
        assert tf.start_wall == pytest.approx(
            flight.anchored_wall() + 1.5, abs=0.2)
        assert tf.wall_now() >= tf.start_wall


# --------------------------------------------------------------------- #
# Timeline merge
# --------------------------------------------------------------------- #

def _mk_digest(host_wall0: float, wall_s: float, *, stall=0.0, dcn=1.0,
               clock=None) -> dict:
    segs = []
    t = 0.0
    if stall:
        segs.append([t, t + stall, "stall"])
        t += stall
    segs.append([t, t + dcn, "dcn"])
    d = {
        "v": 1, "task_id": "merge-t", "state": "done", "note": "",
        "start_wall": host_wall0, "wall_s": wall_s,
        "phases": {"sched_wait": 0.0, "dcn": dcn, "ici": 0.0,
                   "verify": 0.0, "store": 0.0, "stall": stall,
                   "origin": 0.0},
        "other_s": max(0.0, wall_s - dcn - stall),
        "dominant_phase": "stall" if stall > dcn else "dcn",
        "segments": segs,
        "pieces": [[0, 1, 0.0, 0.01, dcn, 1, "", "p:1"]],
        "pieces_total": 1, "pieces_truncated": False,
        "events": [], "events_total": 4, "events_dropped": 0,
    }
    if clock:
        d["clock"] = clock
    return d


class TestTimelineMerge:
    def test_alignment_recovers_injected_offsets(self):
        """Three hosts started simultaneously in TRUE time but with
        skewed clocks; after merging with their clock samples the
        aligned starts agree within the carried error bounds."""
        lens = podlens.PodLens()
        sched_t0 = 1000.0
        for host, off in (("ha", 0.0), ("hb", 0.75), ("hc", -0.4)):
            clock = [[sched_t0 - 0.001 + off, sched_t0 + 0.001 + off,
                      sched_t0]]
            lens.note_flight("merge-t", host,
                             _mk_digest(sched_t0 + off, 1.0, clock=clock))
        rep = lens.timeline("merge-t")
        assert rep["hosts_total"] == 3
        starts = {h["host"]: h["start_wall"] for h in rep["hosts"]}
        errs = {h["host"]: h["align_err_s"] for h in rep["hosts"]}
        for a in starts:
            for b in starts:
                assert abs(starts[a] - starts[b]) <= errs[a] + errs[b]
        assert rep["align_err_max_s"] < 0.05
        offsets = {h["host"]: h["clock_offset_s"] for h in rep["hosts"]}
        assert offsets["hb"] == pytest.approx(0.75, abs=0.01)
        assert offsets["hc"] == pytest.approx(-0.4, abs=0.01)

    def test_slowest_host_and_dominant_phase_named(self):
        lens = podlens.PodLens()
        lens.note_flight("merge-t", "fast1",
                         _mk_digest(10.0, 0.5, dcn=0.5))
        lens.note_flight("merge-t", "fast2",
                         _mk_digest(10.0, 0.6, dcn=0.6))
        lens.note_flight("merge-t", "laggard",
                         _mk_digest(10.0, 4.0, stall=3.0, dcn=1.0))
        rep = lens.timeline("merge-t")
        assert rep["slowest_host"] == "laggard"
        assert rep["dominant_phase"] == "stall"
        assert rep["hosts"][0]["host"] == "laggard"   # sorted slow-first

    def test_render_draws_bars_bound_and_star(self):
        lens = podlens.PodLens()
        lens.note_flight("merge-t", "fast", _mk_digest(10.0, 0.5))
        lens.note_flight("merge-t", "slow",
                         _mk_digest(10.0, 2.0, stall=1.5, dcn=0.5))
        text = podlens.render_timeline(lens.timeline("merge-t"))
        assert "slowest=slow" in text
        assert "align_err<=" in text
        assert "*slow" in text          # slowest starred
        assert "!" in text and "=" in text   # stall + dcn bars
        assert "legend:" in text

    def test_on_demand_extra_digests_merge_but_are_not_retained(self):
        lens = podlens.PodLens()
        lens.note_flight("merge-t", "shipped", _mk_digest(10.0, 1.0))
        extra = {"pulled": _mk_digest(10.0, 3.0, stall=2.0)}
        rep = lens.timeline("merge-t", extra=extra)
        assert rep["hosts_total"] == 2
        assert rep["slowest_host"] == "pulled"
        assert set(lens.digests_for("merge-t")) == {"shipped"}

    def test_task_index_is_bounded(self):
        lens = podlens.PodLens(max_tasks=4)
        for i in range(12):
            lens.note_flight(f"t{i}", "h", _mk_digest(1.0, 1.0))
        assert len(lens._tasks) == 4
        assert lens.timeline("t0") is None

    def test_completion_stats_reads_compact_rows(self):
        d = _mk_digest(10.0, 2.0, stall=1.0, dcn=1.0)
        makespan, ttfb, stall_frac = podlens.completion_stats(d)
        assert makespan == 2.0
        assert ttfb == pytest.approx(0.01)
        assert stall_frac == pytest.approx(0.5)


# --------------------------------------------------------------------- #
# SLO engine
# --------------------------------------------------------------------- #

class TestSLOEngine:
    def test_seeded_degradation_flips_burn_over_threshold(self):
        """The acceptance semantics in miniature: healthy completions
        keep every burn at 0; one stall-heavy completion in a small pod
        burns the 1%-budget stall SLO far past both window thresholds
        and /debug/slo-shaped output names the breached windows."""
        clock = [100.0]
        eng = slo.SLOEngine(clock=lambda: clock[0])
        for _ in range(6):
            eng.note_completion("h-ok", 2.0, ttfb_s=0.1, stall_frac=0.01)
        rep = eng.evaluate()
        sf = next(s for s in rep["slos"] if s["name"] == "stall_fraction")
        assert sf["state"] == "ok"
        eng.note_completion("h-bad", 3.0, ttfb_s=0.2, stall_frac=0.8)
        rep = eng.evaluate()
        sf = next(s for s in rep["slos"] if s["name"] == "stall_fraction")
        assert sf["state"] == "breach"
        breached_windows = [w for w in sf["windows"]
                            if w["state"] == "breach"]
        assert breached_windows, sf
        for w in breached_windows:
            assert w["burn_rate"] >= w["burn_threshold"]
        assert "stall_fraction" in rep["breached"]

    def test_breach_counter_is_edge_triggered(self):
        clock = [0.0]
        eng = slo.SLOEngine(clock=lambda: clock[0])
        eng.note_completion("h", 1.0, stall_frac=0.9)
        eng.evaluate()
        eng.evaluate()
        eng.evaluate()
        rep = eng.evaluate()
        sf = next(s for s in rep["slos"] if s["name"] == "stall_fraction")
        assert sf["breaches_total"] == 1    # one transition, not per eval
        # Recovery then re-breach counts again.
        clock[0] += 4000.0                  # old completion ages out
        for _ in range(3):
            eng.note_completion("h", 1.0, stall_frac=0.0)
        rep = eng.evaluate()
        sf = next(s for s in rep["slos"] if s["name"] == "stall_fraction")
        assert sf["state"] == "ok"
        eng.note_completion("h", 1.0, stall_frac=0.9)
        rep = eng.evaluate()
        sf = next(s for s in rep["slos"] if s["name"] == "stall_fraction")
        assert sf["breaches_total"] == 2

    def test_ratio_sli_reads_fleet_series(self):
        clock = [50.0]
        series = FleetTimeSeries(clock=lambda: clock[0])
        from dragonfly2_tpu.pkg import fleet as fleetlib

        for _ in range(10):
            series.inc(fleetlib.C_REGISTERS)
        for _ in range(8):
            series.inc(fleetlib.C_BACK_SOURCE)
        eng = slo.SLOEngine(series=series, clock=lambda: clock[0])
        rep = eng.evaluate()
        bs = next(s for s in rep["slos"] if s["name"] == "back_source_rate")
        w = bs["windows"][0]
        assert w["events"] == 10 and w["bad"] == 8
        assert w["burn_rate"] == pytest.approx(0.8 / 0.25, rel=1e-3)
        assert bs["state"] == "breach"

    def test_gauge_sli_counts_bad_buckets(self):
        clock = [50.0]
        series = FleetTimeSeries(
            clock=lambda: clock[0],
            sampler=lambda: {"straggler_hosts": 2.0})
        from dragonfly2_tpu.pkg import fleet as fleetlib

        for i in range(5):
            clock[0] += 5.0                # one event per bucket
            series.inc(fleetlib.C_PIECES)
        eng = slo.SLOEngine(series=series, clock=lambda: clock[0])
        rep = eng.evaluate()
        sg = next(s for s in rep["slos"] if s["name"] == "straggler_hosts")
        w = sg["windows"][0]
        assert w["events"] >= 5 and w["bad"] >= 5
        assert sg["state"] == "breach"

    def test_no_data_without_series_or_completions(self):
        eng = slo.SLOEngine()
        rep = eng.evaluate()
        assert all(s["state"] == "no_data" for s in rep["slos"])

    def test_burn_gauges_exported(self):
        from dragonfly2_tpu.pkg import metrics as metrics_mod

        eng = slo.SLOEngine()
        eng.note_completion("h", 1.0, stall_frac=0.9)
        eng.evaluate()
        text = metrics_mod.render()[0].decode()
        assert "dragonfly_tpu_scheduler_slo_burn_rate" in text
        assert 'slo="stall_fraction"' in text
        assert "dragonfly_tpu_scheduler_slo_breaches_total" in text


# --------------------------------------------------------------------- #
# Scheduler service integration (in-process)
# --------------------------------------------------------------------- #

class FakeStream:
    def __init__(self, open_body):
        self.open_body = open_body
        self.to_sched: asyncio.Queue = asyncio.Queue()
        self.to_peer: asyncio.Queue = asyncio.Queue()

    async def send(self, body):
        await self.to_peer.put(body)

    async def recv(self, timeout=None):
        return await self.to_sched.get()


def _svc(**podlens_overrides):
    from dragonfly2_tpu.scheduler.config import SchedulerConfig
    from dragonfly2_tpu.scheduler.service import SchedulerService

    cfg = SchedulerConfig()
    cfg.seed_peer_enabled = False
    cfg.scheduling.retry_interval = 0.05
    for k, v in podlens_overrides.items():
        setattr(cfg.podlens, k, v)
    return SchedulerService(cfg)


def _body(host, peer, task="lens-task"):
    return {"host": {"id": host, "hostname": host, "ip": "127.0.0.1",
                     "port": 1, "upload_port": 2},
            "peer_id": peer, "task_id": task, "url": "http://o/f"}


class TestServiceIntegration:
    def test_register_answers_carry_sched_wall(self, run_async):
        async def body():
            svc = _svc()
            stream = FakeStream(_body("h1", "p1"))
            server = asyncio.ensure_future(svc.announce_peer(stream, None))
            await stream.to_sched.put({"type": "register"})
            msg = await asyncio.wait_for(stream.to_peer.get(), timeout=30)
            assert msg["type"] == "need_back_source"
            assert msg["sched_wall"] > 0
            await stream.to_sched.put(None)
            await asyncio.wait_for(server, timeout=30)

        run_async(body(), timeout=60)

    def test_shipped_digest_feeds_lens_and_slo(self, run_async):
        async def body():
            svc = _svc()
            stream = FakeStream(_body("h1", "p1"))
            server = asyncio.ensure_future(svc.announce_peer(stream, None))
            await stream.to_sched.put({"type": "register"})
            await asyncio.wait_for(stream.to_peer.get(), timeout=30)
            d = _mk_digest(flight.anchored_wall(), 2.0, stall=1.5,
                           dcn=0.5,
                           clock=[[10.0, 10.002, 9.701]])
            await stream.to_sched.put({"type": "download_finished",
                                       "content_length": 8,
                                       "flight": d})
            await stream.to_sched.put(None)
            await asyncio.wait_for(server, timeout=30)
            assert set(svc.pod_lens.digests_for("lens-task")) == {"h1"}
            off, err, n = svc.pod_lens.clock.estimate("h1")
            assert n == 1 and off == pytest.approx(0.3, abs=0.01)
            assert svc.slo.completions_total == 1
            rep = await svc.pod_timeline_report("lens-task")
            assert rep["hosts"][0]["host"] == "h1"
            assert await svc.pod_timeline_report("absent") is None

        run_async(body(), timeout=60)

    def test_timeline_pulls_missing_hosts_on_demand(self, run_async):
        async def body():
            svc = _svc()
            # Two peers register; only h1 ships a digest (h2's stream
            # "crashed" before download_finished).
            for host, peer in (("h1", "p1"), ("h2", "p2")):
                stream = FakeStream(_body(host, peer))
                server = asyncio.ensure_future(
                    svc.announce_peer(stream, None))
                await stream.to_sched.put({"type": "register"})
                await asyncio.wait_for(stream.to_peer.get(), timeout=30)
                if host == "h1":
                    await stream.to_sched.put(
                        {"type": "download_finished",
                         "flight": _mk_digest(10.0, 1.0)})
                await stream.to_sched.put(None)
                await asyncio.wait_for(server, timeout=30)

            pulled = []

            async def fake_pull(host, task_id):
                pulled.append((host.id, task_id))
                return _mk_digest(10.0, 5.0, stall=4.0)

            svc.seed_clients.flight_digest = fake_pull
            rep = await svc.pod_timeline_report("lens-task")
            assert pulled == [("h2", "lens-task")]
            assert rep["hosts_total"] == 2
            assert rep["slowest_host"] == "h2"
            # Pulled digests are not retained as shipped.
            assert set(svc.pod_lens.digests_for("lens-task")) == {"h1"}

        run_async(body(), timeout=60)

    def test_announce_host_clock_sample_and_scorecard(self, run_async):
        async def body():
            svc = _svc()
            resp = await svc.announce_host(
                {"id": "ah-1", "hostname": "ah", "ip": "1.1.1.1",
                 "port": 9, "upload_port": 10,
                 "clock": {"t0": 100.2, "t1": 100.202, "echo": 100.0}},
                None)
            assert resp["ok"] and resp["sched_wall"] > 0
            off, err, n = svc.pod_lens.clock.estimate("ah-1")
            assert n == 1 and off == pytest.approx(0.201, abs=0.01)
            # Once the fleet has a scorecard row it rides the response.
            svc.fleet.scorecards.note_serve("ah-1", 12.0)
            resp = await svc.announce_host(
                {"id": "ah-1", "hostname": "ah", "ip": "1.1.1.1"}, None)
            assert resp["scorecard"]["serve_ewma_ms"] == 12.0
            assert resp["scorecard"]["straggler"] is False

        run_async(body(), timeout=60)

    def test_podlens_disabled_removes_surfaces(self, run_async):
        async def body():
            svc = _svc(enabled=False)
            assert svc.pod_lens is None and svc.slo is None
            assert await svc.pod_timeline_report("x") is None

        run_async(body(), timeout=30)


# --------------------------------------------------------------------- #
# Conductor ships the digest (in-process, fake scheduler)
# --------------------------------------------------------------------- #

class TestConductorShipping:
    def test_terminal_message_carries_digest_and_clock(self, run_async,
                                                       tmp_path):
        from tests.test_chaos import (
            FakeAnnounceStream,
            FakeSchedulerClient,
            _make_conductor,
        )

        async def body():
            announce = FakeAnnounceStream([{
                "type": "normal_task",
                "task": {"content_length": 8, "piece_size": 4,
                         "total_piece_count": 2},
                "parents": [],
                "sched_wall": flight.anchored_wall() - 0.25,
            }])
            sched = FakeSchedulerClient([announce])
            c = _make_conductor(tmp_path, sched)
            # Both pieces already on disk: the pull completes instantly.
            await c.run()
            finals = [m for m in announce.sent
                      if m.get("type") == "download_finished"]
            assert finals, announce.sent
            d = finals[-1]["flight"]
            assert d["task_id"] == "chaos-t"
            assert set(d["phases"]) == set(flight.PHASES)
            assert d["bytes"] <= flight.DIGEST_MAX_BYTES
            # The register round trip became a clock sample with the
            # scheduler's echo in the middle.
            assert len(d["clock"]) == 1
            t0, t1, echo = d["clock"][0]
            assert t0 <= t1
            assert echo == pytest.approx(t0 + 0.25, abs=2.0)

        run_async(body(), timeout=60)


# --------------------------------------------------------------------- #
# Debug endpoints
# --------------------------------------------------------------------- #

class TestEndpoints:
    def test_slo_and_timeline_routes(self, run_async):
        import aiohttp

        from dragonfly2_tpu.pkg.metrics_server import MetricsServer

        async def body():
            lens = podlens.PodLens()
            lens.note_flight("ep-t", "h-slow",
                             _mk_digest(10.0, 2.0, stall=1.5))
            lens.note_flight("ep-t", "h-fast", _mk_digest(10.0, 0.5))
            eng = slo.SLOEngine()
            eng.note_completion("h-slow", 2.0, stall_frac=0.75)

            async def provider(task_id):
                return lens.timeline(task_id)

            srv = MetricsServer(slo=eng, pod_timeline=provider)
            port = await srv.serve("127.0.0.1", 0)
            base = f"http://127.0.0.1:{port}"
            try:
                async with aiohttp.ClientSession() as sess:
                    async with sess.get(f"{base}/debug/slo") as r:
                        assert r.status == 200
                        rep = await r.json()
                    names = {s["name"] for s in rep["slos"]}
                    assert {"broadcast_makespan", "stall_fraction",
                            "back_source_rate"} <= names
                    async with sess.get(
                            f"{base}/debug/pod/ep-t/timeline") as r:
                        assert r.status == 200
                        tl = await r.json()
                    assert tl["slowest_host"] == "h-slow"
                    async with sess.get(f"{base}/debug/pod/ep-t/timeline",
                                        params={"format": "text"}) as r:
                        text = await r.text()
                    assert "slowest=h-slow" in text
                    assert "align_err<=" in text
                    async with sess.get(
                            f"{base}/debug/pod/absent/timeline") as r:
                        assert r.status == 404
            finally:
                await srv.close()

        run_async(body(), timeout=60)

    def test_routes_404_without_providers(self, run_async):
        import aiohttp

        from dragonfly2_tpu.pkg.metrics_server import MetricsServer

        async def body():
            srv = MetricsServer()
            port = await srv.serve("127.0.0.1", 0)
            try:
                async with aiohttp.ClientSession() as sess:
                    for path in ("/debug/slo", "/debug/pod/x/timeline"):
                        async with sess.get(
                                f"http://127.0.0.1:{port}{path}") as r:
                            assert r.status == 404, path
            finally:
                await srv.close()

        run_async(body(), timeout=60)


# --------------------------------------------------------------------- #
# Decision-log time filters (satellite)
# --------------------------------------------------------------------- #

class TestDecisionTimeFilters:
    def test_since_before_and_truncation(self, monkeypatch):
        from dragonfly2_tpu.pkg.fleet import DecisionLog

        dl = DecisionLog(cap=64)
        t = [1000.0]
        monkeypatch.setattr("dragonfly2_tpu.pkg.fleet.time",
                            type("T", (), {"time": lambda: t[0]}))
        for i in range(20):
            t[0] = 1000.0 + i
            dl.record("handout", task=f"t{i}", host="h")
        page = dl.query(since=1005.0, before=1010.0)
        assert [d["ts"] for d in page["decisions"]] == [
            1009.0, 1008.0, 1007.0, 1006.0, 1005.0]
        assert page["truncated"] is False
        page = dl.query(limit=3)
        assert len(page["decisions"]) == 3
        assert page["truncated"] is True
        assert page["decisions"][0]["ts"] == 1019.0
        # Paging back with before= walks older entries.
        older = dl.query(limit=3, before=page["decisions"][-1]["ts"])
        assert older["decisions"][0]["ts"] == 1016.0
        # A filter that matches everything scanned but nothing beyond
        # the limit is NOT truncated.
        exact = dl.query(since=1018.0)
        assert len(exact["decisions"]) == 2
        assert exact["truncated"] is False


# --------------------------------------------------------------------- #
# Chaos-seeded 4-host REAL-process pod e2e (the acceptance case)
# --------------------------------------------------------------------- #

E2E_CONTENT = bytes(random.Random(88).randbytes(12 * 1024 * 1024))
TRUE_OFFSET_S = 0.35


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_cli(args, log_path, env_extra=None):
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env.update(env_extra or {})
    logf = open(log_path, "w")
    return subprocess.Popen(
        [sys.executable, "-m", "dragonfly2_tpu.cli.main", *args],
        stdout=logf, stderr=subprocess.STDOUT, env=env)


async def _start_e2e_origin():
    from aiohttp import web

    from dragonfly2_tpu.pkg.piece import Range

    async def blob(request):
        rng = request.headers.get("Range")
        if rng:
            r = Range.parse_http(rng, len(E2E_CONTENT))
            data = E2E_CONTENT[r.start:r.start + r.length]
            return web.Response(status=206, body=data, headers={
                "Accept-Ranges": "bytes",
                "Content-Range": f"bytes {r.start}-"
                                 f"{r.start + r.length - 1}/"
                                 f"{len(E2E_CONTENT)}"})
        return web.Response(body=E2E_CONTENT,
                            headers={"Accept-Ranges": "bytes"})

    app = web.Application()
    app.router.add_get("/pod.bin", blob)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    return runner, site._server.sockets[0].getsockname()[1]


class TestPodLensE2E:
    """Real scheduler + 4 real daemon processes + chaos: the merged
    timeline must name the seeded slow host, agree with every host's own
    autopsy, carry an alignment bound covering the injected clock skew,
    and the seeded degradation must breach an SLO at /debug/slo."""

    def test_chaos_pod_timeline_and_slo(self, run_async, tmp_path):
        import glob
        import hashlib
        import os
        import subprocess

        import aiohttp

        sha = hashlib.sha256(E2E_CONTENT).hexdigest()

        async def wait_sock(path, timeout=90.0):
            deadline = asyncio.get_running_loop().time() + timeout
            while asyncio.get_running_loop().time() < deadline:
                if os.path.exists(path):
                    return True
                await asyncio.sleep(0.1)
            return False

        async def run():
            runner, origin_port = await _start_e2e_origin()
            url = f"http://127.0.0.1:{origin_port}/pod.bin"
            sched_port = _free_port()
            sched_metrics = _free_port()
            procs = []
            homes = {}
            metrics_ports = {}
            try:
                procs.append(_spawn_cli(
                    ["scheduler", "--host", "127.0.0.1",
                     "--port", str(sched_port),
                     "--metrics-port", str(sched_metrics)],
                    str(tmp_path / "sched.log")))

                # The seeded schedule: pod-slow's piece bodies stall 2 s
                # before the first chunk (silent parent — the flight
                # recorder books it as stall); pod-a sees ONE corrupt
                # body (crc reject + retry); pod-slow's clock is skewed
                # by a known 350 ms the alignment must recover.
                daemons = {
                    "pod-seed": ([], {}),
                    "pod-a": ([], {"DF_CHAOS": json.dumps({
                        "seed": 11, "rules": [{
                            "site": "piece.body", "kind": "corrupt",
                            "rate": 1.0, "max_fires": 1}]})}),
                    "pod-b": ([], {}),
                    "pod-slow": (
                        ["--clock-offset", str(TRUE_OFFSET_S)],
                        {"DF_CHAOS": json.dumps({
                            "seed": 7, "rules": [{
                                "site": "piece.body", "kind": "stall",
                                "rate": 1.0, "stall_s": 2.0,
                                "max_fires": 3}]})}),
                }
                for name, (extra, env) in daemons.items():
                    home = str(tmp_path / name)
                    homes[name] = home
                    metrics_ports[name] = _free_port()
                    args = ["daemon", "--work-home", home,
                            "--hostname", name,
                            "--scheduler", f"127.0.0.1:{sched_port}",
                            "--metrics-port",
                            str(metrics_ports[name]), *extra]
                    if name == "pod-seed":
                        args.append("--seed-peer")
                    procs.append(_spawn_cli(
                        args, str(tmp_path / f"{name}.log"), env))
                for name, home in homes.items():
                    ok = await wait_sock(f"{home}/run/dfdaemon.sock")
                    assert ok, open(tmp_path / f"{name}.log").read()[-2000:]

                def dfget(name, out, extra=()):
                    return _spawn_cli(
                        ["dfget", url, "-O", out,
                         "--work-home", homes[name], "--no-daemon",
                         "--digest", f"sha256:{sha}", *extra],
                        out + ".log")

                async def await_dl(proc, out):
                    rc = await asyncio.to_thread(proc.wait, 180)
                    assert rc == 0, open(out + ".log").read()[-2000:]
                    with open(out, "rb") as f:
                        got = hashlib.sha256(f.read()).hexdigest()
                    assert got == sha

                # Warm phase: pod-a (corrupt chaos) + pod-b (clean, with
                # --explain --pod exercising the full CLI surface).
                out_a = str(tmp_path / "out-a.bin")
                out_b = str(tmp_path / "out-b.bin")
                dl_a = dfget("pod-a", out_a)
                dl_b = dfget("pod-b", out_b, ("--explain", "--pod"))
                await asyncio.gather(await_dl(dl_a, out_a),
                                     await_dl(dl_b, out_b))
                # The slow host joins a WARM pod: its wall is dominated
                # by the seeded stalls, not by seed-fetch scheduling.
                out_s = str(tmp_path / "out-slow.bin")
                await await_dl(dfget("pod-slow", out_s), out_s)

                # dfget --explain --pod rendered both waterfalls.
                cli_log = open(out_b + ".log").read()
                assert "phase breakdown:" in cli_log, cli_log[-2000:]
                assert "\npod " in cli_log or cli_log.startswith("pod "), \
                    cli_log[-2000:]
                assert "legend:" in cli_log

                task_id = None
                for meta_path in glob.glob(
                        f"{homes['pod-b']}/**/metadata.json",
                        recursive=True):
                    task_id = json.load(open(meta_path))["task_id"]
                assert task_id

                base = f"http://127.0.0.1:{sched_metrics}"
                async with aiohttp.ClientSession() as sess:
                    # -- merged timeline ------------------------------- #
                    async with sess.get(
                            f"{base}/debug/pod/{task_id}/timeline") as r:
                        assert r.status == 200, await r.text()
                        tl = await r.json()
                    assert tl["hosts_total"] >= 4, tl
                    rows = {h["host"]: h for h in tl["hosts"]}
                    slow_rows = [h for hid, h in rows.items()
                                 if hid.startswith("pod-slow-")]
                    assert slow_rows, rows.keys()
                    slow = slow_rows[0]
                    # The seeded host is named slowest, stall/dcn
                    # dominant.
                    assert tl["slowest_host"].startswith("pod-slow-"), tl
                    assert slow["dominant_phase"] in ("stall", "dcn"), \
                        slow
                    assert slow["phases"]["stall"] >= 1.0, slow
                    # The alignment bound covers the injected offset.
                    assert abs(slow["clock_offset_s"] - TRUE_OFFSET_S) \
                        <= slow["align_err_s"] + 0.005, slow
                    assert slow["clock_samples"] >= 1
                    # Unskewed hosts estimate ~zero offset.
                    for hid, h in rows.items():
                        if not hid.startswith("pod-slow-") \
                                and h["clock_samples"]:
                            assert abs(h["clock_offset_s"]) \
                                <= h["align_err_s"] + 0.005, h

                    # -- per-host agreement with own autopsies --------- #
                    for name, mport in metrics_ports.items():
                        hrow = next(
                            (h for hid, h in rows.items()
                             if hid.startswith(f"{name}-")), None)
                        assert hrow is not None, (name, rows.keys())
                        async with sess.get(
                                f"http://127.0.0.1:{mport}"
                                f"/debug/flight/{task_id}") as r:
                            assert r.status == 200, name
                            own = await r.json()
                        tol = 0.05 * max(own["wall_s"],
                                         hrow["wall_s"]) + 0.05
                        for ph in ("stall", "dcn", "origin", "ici"):
                            assert abs(hrow["phases"][ph]
                                       - own["phases"][ph]) <= tol, (
                                name, ph, hrow["phases"], own["phases"])

                    # -- text waterfall -------------------------------- #
                    async with sess.get(
                            f"{base}/debug/pod/{task_id}/timeline",
                            params={"format": "text"}) as r:
                        text = await r.text()
                    assert "slowest=pod-slow-" in text
                    assert "align_err<=" in text
                    assert "*pod-slow-" in text

                    # -- SLO breach ------------------------------------ #
                    async with sess.get(f"{base}/debug/slo") as r:
                        assert r.status == 200
                        slo_rep = await r.json()
                    sf = next(s for s in slo_rep["slos"]
                              if s["name"] == "stall_fraction")
                    assert sf["state"] == "breach", slo_rep
                    breached = [w for w in sf["windows"]
                                if w["state"] == "breach"]
                    assert breached, sf
                    for w in breached:
                        assert w["burn_rate"] >= w["burn_threshold"]
                    assert "stall_fraction" in slo_rep["breached"]
                    async with sess.get(f"{base}/metrics") as r:
                        metrics_text = await r.text()
                    assert ("dragonfly_tpu_scheduler_slo_burn_rate"
                            in metrics_text)
                    assert 'slo="stall_fraction"' in metrics_text
            finally:
                import signal

                for p in procs:
                    if p.poll() is None:
                        p.send_signal(signal.SIGTERM)
                for p in procs:
                    try:
                        p.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        p.kill()
                await runner.cleanup()

        run_async(run(), timeout=420)


# --------------------------------------------------------------------- #
# Wire schema
# --------------------------------------------------------------------- #

class TestWireSchema:
    def test_flight_digest_on_terminal_messages(self):
        from dragonfly2_tpu.proto import wire

        wire.validate_stream_msg("Scheduler.AnnouncePeer", {
            "type": "download_finished", "content_length": 8,
            "flight": {"v": 1, "task_id": "t", "wall_s": 1.0,
                       "phases": {}, "segments": [], "pieces": [],
                       "clock": [[1.0, 1.01, 0.7]]}})
        wire.validate_stream_msg("Scheduler.AnnouncePeer", {
            "type": "download_failed", "reason": "x",
            "flight": {"v": 1}})
        with pytest.raises(wire.SchemaError, match="flight"):
            wire.validate_stream_msg("Scheduler.AnnouncePeer", {
                "type": "download_finished", "flight": "nope"})

    def test_announce_host_clock_sample(self):
        from dragonfly2_tpu.proto import wire

        wire.validate_unary("Scheduler.AnnounceHost", {
            "id": "h", "clock": {"t0": 1.0, "t1": 1.01, "echo": 0.7}})
        with pytest.raises(wire.SchemaError, match="echo"):
            wire.validate_unary("Scheduler.AnnounceHost", {
                "id": "h", "clock": {"t0": 1.0, "t1": 1.01}})

    def test_pod_timeline_unaries(self):
        from dragonfly2_tpu.proto import wire

        wire.validate_unary("Scheduler.PodTimeline", {"task_id": "t"})
        wire.validate_unary("Daemon.PodTimeline", {"task_id": "t"})
        for method in ("Scheduler.PodTimeline", "Daemon.PodTimeline"):
            with pytest.raises(wire.SchemaError, match="task_id"):
                wire.validate_unary(method, {})
