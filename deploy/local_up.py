"""Compose-free localhost fabric launcher.

Boots the docker-compose topology (manager + scheduler + seed peer + N
peers) as plain processes for machines without docker — e.g. a TPU VM
where the fabric runs straight on the host. Ctrl-C tears everything down.

  python deploy/local_up.py [--peers 2] [--base-dir /tmp/df-fabric]
  python deploy/local_up.py --smoke   # boot, dfget a test blob, exit

Ports (host-local): manager REST 18080 / drpc 18065, scheduler 18002;
daemon ports are ephemeral (printed at boot).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

MANAGER_REST = 18080
MANAGER_GRPC = 18065
SCHEDULER_PORT = 18002


def _spawn(args: list[str], log_path: str) -> subprocess.Popen:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    logf = open(log_path, "w")
    return subprocess.Popen(
        [sys.executable, "-m", "dragonfly2_tpu.cli.main", *args],
        stdout=logf, stderr=subprocess.STDOUT, env=env)


def _wait_http(url: str, timeout: float = 30.0) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            urllib.request.urlopen(url, timeout=2)
            return True
        except Exception:
            time.sleep(0.2)
    return False


def _wait_sock(path: str, timeout: float = 30.0) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            s = socket.socket(socket.AF_UNIX)
            try:
                s.connect(path)
                return True
            except OSError:
                pass
            finally:
                s.close()
        time.sleep(0.2)
    return False


def up(base_dir: str, n_peers: int) -> tuple[list[subprocess.Popen], dict]:
    os.makedirs(base_dir, exist_ok=True)
    procs: list[subprocess.Popen] = []
    homes = {}

    procs.append(_spawn(
        ["manager", "--host", "127.0.0.1", "--port", str(MANAGER_REST),
         "--grpc-port", str(MANAGER_GRPC),
         "--db", os.path.join(base_dir, "manager.db")],
        os.path.join(base_dir, "manager.log")))
    if not _wait_http(f"http://127.0.0.1:{MANAGER_REST}/healthy"):
        raise RuntimeError("manager did not come up; see manager.log")

    procs.append(_spawn(
        ["scheduler", "--host", "127.0.0.1", "--port", str(SCHEDULER_PORT),
         "--manager", f"127.0.0.1:{MANAGER_GRPC}"],
        os.path.join(base_dir, "scheduler.log")))

    roles = [("seed", True)] + [(f"peer{i + 1}", False) for i in range(n_peers)]
    for name, is_seed in roles:
        home = os.path.join(base_dir, name)
        homes[name] = home
        args = ["daemon", "--work-home", home,
                "--scheduler", f"127.0.0.1:{SCHEDULER_PORT}",
                "--manager", f"127.0.0.1:{MANAGER_GRPC}"]
        if is_seed:
            args.append("--seed-peer")
        procs.append(_spawn(args, os.path.join(base_dir, f"{name}.log")))
    for name, _ in roles:
        sock = os.path.join(homes[name], "run", "dfdaemon.sock")
        if not _wait_sock(sock):
            raise RuntimeError(f"{name} did not come up; see {name}.log")

    return procs, homes


def down(procs: list[subprocess.Popen]) -> None:
    for p in reversed(procs):
        try:
            p.send_signal(signal.SIGTERM)
        except ProcessLookupError:
            pass
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()


def smoke(base_dir: str, homes: dict) -> None:
    """Serve a blob from this process and dfget it through peer1."""
    import hashlib
    import random
    import threading
    from http.server import BaseHTTPRequestHandler, HTTPServer

    content = random.Random(5).randbytes(4 << 20)
    sha = hashlib.sha256(content).hexdigest()

    class H(BaseHTTPRequestHandler):
        def do_GET(self):
            try:
                self.send_response(200)
                self.send_header("Content-Length", str(len(content)))
                self.send_header("Accept-Ranges", "bytes")
                self.end_headers()
                self.wfile.write(content)
            except OSError:
                pass  # probe disconnects are expected

        def log_message(self, *a):
            pass

    httpd = HTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{httpd.server_port}/blob"
    out = os.path.join(base_dir, "smoke.bin")
    rc = subprocess.run(
        [sys.executable, "-m", "dragonfly2_tpu.cli.main", "dfget", url,
         "-O", out, "--work-home", homes["peer1"], "--no-daemon",
         "--digest", f"sha256:{sha}"],
        env={**os.environ, "PYTHONPATH": REPO}).returncode
    httpd.shutdown()
    if rc != 0:
        raise RuntimeError("smoke dfget failed")
    with open(out, "rb") as f:
        if hashlib.sha256(f.read()).hexdigest() != sha:
            raise RuntimeError("smoke sha mismatch")
    print("smoke: dfget through the fabric OK")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--peers", type=int, default=2)
    ap.add_argument("--base-dir", default="/tmp/df-fabric")
    ap.add_argument("--smoke", action="store_true",
                    help="boot, run one dfget through peer1, tear down")
    args = ap.parse_args()

    procs, homes = up(args.base_dir, args.peers)
    print(json.dumps({
        "manager_rest": f"http://127.0.0.1:{MANAGER_REST}",
        "scheduler": f"127.0.0.1:{SCHEDULER_PORT}",
        "daemons": {n: os.path.join(h, "run", "dfdaemon.sock")
                    for n, h in homes.items()},
    }, indent=2))
    try:
        if args.smoke:
            smoke(args.base_dir, homes)
            return 0
        print("fabric up — Ctrl-C to stop")
        signal.sigwait({signal.SIGINT, signal.SIGTERM})
        return 0
    finally:
        down(procs)


if __name__ == "__main__":
    sys.exit(main())
